# Pipeline framework: a dataflow DAG of PipelineElements processing streams
# of frames.
#
# Capability parity with the reference pipeline framework
# (reference: aiko_services/pipeline.py:116-938):
#   * JSON pipeline definition — version / name / runtime / graph DSL /
#     parameters / per-element definitions with local or remote deploy
#     (reference schema: pipeline.py:753-866, dataclasses :137-173);
#   * PipelineGraph — Graph + dataflow validation: every declared element
#     input must be produced by a predecessor output or renamed through an
#     explicit fan-in edge mapping (reference: pipeline.py:177-260);
#   * PipelineElement — create_frame / get_parameter / process_frame /
#     start_stream / stop_stream; every element is an Actor, so it is
#     independently addressable and dashboard-visible
#     (reference: pipeline.py:270-338);
#   * Streams — leased lifecycles with per-stream parameters; frames extend
#     the lease; expiry destroys the stream (reference: pipeline.py:717-749);
#   * per-frame metrics: per-element and cumulative wall time stamped into
#     the frame context (reference: pipeline.py:639-703);
#   * remote elements: placeholder swapped for a discovered proxy when the
#     remote service appears (reference: pipeline.py:340-362, :591-620).
#
# TPU-native design changes (SURVEY.md §7):
#   * frames carry a "swag" dict whose values may be jax.Arrays — co-located
#     elements hand tensors to each other on-device with no serialization
#     (the reference zlib+np.save's tensors through an MQTT broker);
#   * element process_frame may return a third value `defer` — a callable
#     resolved later — enabling overlapped device execution (jax dispatch is
#     async; the host DAG walk does not block on device completion);
#   * an element failure destroys the failing stream only, not the process
#     (the reference exits the whole process, pipeline.py:704-710);
#   * deterministic: runs entirely on the EventEngine, so multi-pipeline
#     systems are testable with a VirtualClock in one pytest process.

from __future__ import annotations

import itertools
import json
import random
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

from .actor import Actor, get_remote_proxy
from .lease import Lease
from .observe import tracing
from .observe.metrics import MirroredStats, default_registry
from .service import ServiceFilter, ServiceProtocol, ServiceTags
from .share import ServicesCache
from .transport import wire
from .utils import (
    Graph, GraphError, get_logger, jittered_backoff, load_class,
    load_module,
)

__all__ = [
    "PROTOCOL_PIPELINE", "PipelineDefinition", "PipelineElementDefinition",
    "PipelineGraph", "PipelineElement", "Pipeline", "Stream", "Frame",
    "FrameOutput", "DEFERRED", "parse_pipeline_definition",
    "load_pipeline_definition", "definition_to_dict", "PipelineError",
    "lookup_contract",
]

PROTOCOL_PIPELINE = ServiceProtocol("pipeline")
DEFINITION_VERSION = 0
STREAM_LEASE_TIME = 60.0          # reference: pipeline.py:128
DEFAULT_STREAM_ID = "*"


class PipelineError(ValueError):
    pass


def lookup_contract(contracts: dict, name: str, direction: str):
    """The one contract-lookup rule: a direction-prefixed key
    ("in:audio"/"out:audio") beats a plain one ("audio").  Shared by
    PipelineElementDefinition.contract_for and the static checker's
    class-attribute fallback so the two can never drift."""
    return contracts.get(f"{direction}:{name}", contracts.get(name))


# ---------------------------------------------------------------------------
# Definition schema
# ---------------------------------------------------------------------------

@dataclass
class PipelineElementDefinition:
    """One element in a pipeline definition.

    deploy is either local —  {"local": {"module": ..., "class_name": ...}}
    — or remote — {"remote": {"service_filter": {...}}} (reference:
    pipeline.py:156-173).

    contracts maps io names to dtype/shape/codec contract strings (see
    analysis/contracts.py), e.g. {"audio": "f32[*] | mulaw-u8[*]"}.
    Prefix a key "in:"/"out:" when the same name needs different
    contracts per direction; a plain key covers both.  Declared either
    here, per io item ({"name": "audio", "contract": "f32[*]"}), or as
    a class-level `contracts` attribute on the element class — the
    static checker (python -m aiko_services_tpu.analysis) proves
    producer/consumer compatibility per edge before deployment."""
    name: str
    input: list = field(default_factory=list)    # [{"name":..,"type":..}]
    output: list = field(default_factory=list)
    parameters: dict = field(default_factory=dict)
    deploy: dict = field(default_factory=dict)
    contracts: dict = field(default_factory=dict)

    @property
    def input_names(self) -> list[str]:
        return [item["name"] for item in self.input]

    @property
    def output_names(self) -> list[str]:
        return [item["name"] for item in self.output]

    @property
    def is_remote(self) -> bool:
        return "remote" in self.deploy

    def contract_for(self, name: str, direction: str) -> str | None:
        """Contract string for an io name; direction is "in" or "out"."""
        return lookup_contract(self.contracts, name, direction)


@dataclass
class PipelineDefinition:
    version: int
    name: str
    runtime: str
    graph: list                    # list of graph-DSL strings
    parameters: dict = field(default_factory=dict)
    elements: list = field(default_factory=list)

    def element(self, name: str) -> PipelineElementDefinition:
        for element in self.elements:
            if element.name == name:
                return element
        raise PipelineError(f"no element definition: {name}")


_RUNTIMES = ("python", "jax", "tpu")


def parse_pipeline_definition(data: dict,
                              source: str = "<dict>") -> PipelineDefinition:
    """Validate + build a PipelineDefinition from a parsed JSON dict.

    Explicit structural validation replacing the reference's embedded Avro
    schema (reference: pipeline.py:512-589, :753-866)."""
    def fail(msg):
        raise PipelineError(f"pipeline definition {source}: {msg}")

    if not isinstance(data, dict):
        fail("top level must be an object")
    for key in ("version", "name", "runtime", "graph", "elements"):
        if key not in data:
            fail(f"missing required field {key!r}")
    if data["version"] != DEFINITION_VERSION:
        fail(f"version must be {DEFINITION_VERSION}, got {data['version']!r}")
    if data["runtime"] not in _RUNTIMES:
        fail(f"runtime must be one of {_RUNTIMES}, got {data['runtime']!r}")
    graph = data["graph"]
    if isinstance(graph, str):
        graph = [graph]
    if not isinstance(graph, list) or not graph or \
            not all(isinstance(g, str) for g in graph):
        fail("graph must be a non-empty list of DSL strings")
    parameters = data.get("parameters", {})
    if not isinstance(parameters, dict):
        fail("parameters must be an object")

    elements = []
    seen = set()
    for index, raw in enumerate(data["elements"]):
        where = f"elements[{index}]"
        if not isinstance(raw, dict) or "name" not in raw:
            fail(f"{where}: must be an object with a name")
        name = raw["name"]
        if name in seen:
            fail(f"{where}: duplicate element name {name!r}")
        seen.add(name)
        contracts = raw.get("contracts", {})
        if not isinstance(contracts, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in contracts.items()):
            fail(f"{where}.contracts: must map io names to contract "
                 f"strings")
        contracts = dict(contracts)
        for io_key, prefix in (("input", "in:"), ("output", "out:")):
            for io_item in raw.get(io_key, []):
                if not isinstance(io_item, dict) or "name" not in io_item:
                    fail(f"{where}.{io_key}: entries need a name")
                if "contract" in io_item:
                    if not isinstance(io_item["contract"], str):
                        fail(f"{where}.{io_key}: contract must be a "
                             f"string")
                    contracts.setdefault(prefix + io_item["name"],
                                         io_item["contract"])
        deploy = raw.get("deploy", {})
        if deploy:
            if set(deploy) - {"local", "remote"} or len(deploy) != 1:
                fail(f"{where}.deploy: exactly one of local|remote")
            if "local" in deploy and "class_name" not in deploy["local"]:
                fail(f"{where}.deploy.local: needs class_name")
            if "remote" in deploy and "service_filter" not in deploy["remote"]:
                fail(f"{where}.deploy.remote: needs service_filter")
        elements.append(PipelineElementDefinition(
            name=name,
            input=list(raw.get("input", [])),
            output=list(raw.get("output", [])),
            parameters=dict(raw.get("parameters", {})),
            deploy=dict(deploy),
            contracts=contracts))

    return PipelineDefinition(
        version=data["version"], name=data["name"], runtime=data["runtime"],
        graph=graph, parameters=dict(parameters), elements=elements)


def definition_to_dict(definition: PipelineDefinition) -> dict:
    """Inverse of parse_pipeline_definition: a plain dict that
    round-trips through parse (and through json/yaml files — the
    reference CLI's `--dump yaml/json` export, reference
    cli.py:219-231).  Empty optional fields are elided so the dump
    matches a hand-written definition."""
    elements = []
    for element in definition.elements:
        raw = {"name": element.name}
        if element.input:
            raw["input"] = list(element.input)
        if element.output:
            raw["output"] = list(element.output)
        if element.parameters:
            raw["parameters"] = dict(element.parameters)
        if element.deploy:
            raw["deploy"] = dict(element.deploy)
        if element.contracts:
            raw["contracts"] = dict(element.contracts)
        elements.append(raw)
    data = {"version": definition.version, "name": definition.name,
            "runtime": definition.runtime, "graph": list(definition.graph),
            "elements": elements}
    if definition.parameters:
        data["parameters"] = dict(definition.parameters)
    return data


def load_pipeline_definition(pathname: str) -> PipelineDefinition:
    """Load a definition from JSON or (by extension) YAML — the dump
    export round-trips through either format."""
    with open(pathname) as f:
        if pathname.endswith((".yaml", ".yml")):
            try:
                import yaml
            except ImportError as exc:      # pragma: no cover
                raise PipelineError(
                    f"{pathname}: .yaml definitions need pyyaml "
                    f"(pip install pyyaml)") from exc
            data = yaml.safe_load(f)
        else:
            data = json.load(f)
    return parse_pipeline_definition(data, source=pathname)


# ---------------------------------------------------------------------------
# Graph with dataflow validation
# ---------------------------------------------------------------------------

class PipelineGraph(Graph):
    """Pipeline DAG: nodes carry elements; edges may carry name mappings
    "(PE_1 (PE_2 (a: x)))" meaning PE_1's output `a` feeds PE_2's input `x`
    (reference mapping capture: pipeline.py:418-427)."""

    def __init__(self):
        super().__init__()
        # (tail, head) -> {producer_output_name: consumer_input_name}
        self.mappings: dict[tuple[str, str], dict] = {}

    @classmethod
    def from_definition(cls,
                        definition: PipelineDefinition) -> "PipelineGraph":
        graph = cls()

        def capture(tail, head, properties):
            graph.mappings[(tail, head)] = dict(properties)

        parsed = Graph.traverse(definition.graph, capture)
        graph._nodes = parsed._nodes
        graph._head_names = parsed._head_names
        # re-key captured properties (traverse stores them on nodes too)
        for node in graph.nodes():
            for head, properties in node.properties.items():
                graph.mappings.setdefault((node.name, head),
                                          dict(properties))
        for name in graph.node_names():
            definition.element(name)        # every node must be defined
        return graph

    def validate(self, definition: PipelineDefinition) -> None:
        """Every element input must be satisfiable: produced upstream under
        the same name, renamed onto it by an edge mapping, or provided by
        the stream swag for head nodes (reference: pipeline.py:230-260)."""
        preds = self.predecessor_map()
        for node in self.topological_order():
            element_def = definition.element(node.name)
            if not preds[node.name]:
                continue        # head node: inputs come from the frame swag
            available: set[str] = set()
            for pred in preds[node.name]:
                pred_outputs = definition.element(pred).output_names
                mapping = self.mappings.get((pred, node.name), {})
                for output_name in pred_outputs:
                    available.add(mapping.get(output_name, output_name))
            missing = [name for name in element_def.input_names
                       if name not in available]
            if missing:
                raise PipelineError(
                    f"element {node.name}: inputs {missing} not produced by "
                    f"predecessors {preds[node.name]} (add an edge mapping?)")


# ---------------------------------------------------------------------------
# Streams and frames
# ---------------------------------------------------------------------------

@dataclass
class Stream:
    """A leased sequence of frames flowing through the pipeline."""
    stream_id: str
    parameters: dict = field(default_factory=dict)
    frame_id: int = 0
    state: str = "run"              # run | stop
    lease: Lease | None = None
    variables: dict = field(default_factory=dict)   # element scratch space
    consecutive_failures: int = 0   # frame failures since the last success
    last_diagnostic: str = ""       # why the most recent frame failed
    parked: list = field(default_factory=list)      # DEFERRED frames

    def next_frame_id(self) -> int:
        frame_id = self.frame_id
        self.frame_id += 1
        return frame_id


@dataclass(eq=False)        # identity semantics: Stream.parked removal
class Frame:
    """One unit of work: stream context + named values ("swag")."""
    stream: Stream
    frame_id: int
    swag: dict = field(default_factory=dict)
    metrics: dict = field(default_factory=dict)
    deferred_at: int | None = None      # topo index parked at (batching)
    deferred_since: float = 0.0
    reply_to: tuple | None = None       # (topic, hop_id): remote serving
    reply_skip: dict | None = None      # original remote inputs: values
                                        # still identical at reply time
                                        # are not echoed back
    # distributed trace position + end-to-end deadline (ISSUE 5): set
    # from the ambient context (remote frames arrive under the caller's
    # context) or minted fresh when the pipeline has a frame_deadline
    trace: "tracing.TraceContext | None" = None

    @property
    def stream_id(self) -> str:
        return self.stream.stream_id


class _Deferred:
    """Sentinel: element submitted async work (e.g. to a batching
    scheduler) and will call pipeline.resume_frame(frame, name, outputs)
    when it completes.  Return `FrameOutput(True, DEFERRED)`."""

    def __repr__(self):
        return "DEFERRED"


DEFERRED = _Deferred()


class FrameOutput:
    """Element result: ok + named outputs.  `outputs=None` with ok=True means
    "frame consumed" (sink / windowing elements that emit nothing)."""
    __slots__ = ("ok", "outputs", "diagnostic")

    def __init__(self, ok: bool, outputs: dict | None = None,
                 diagnostic: str = ""):
        self.ok = ok
        self.outputs = outputs
        self.diagnostic = diagnostic

    def __iter__(self):     # allow  ok, outputs = element.process_frame(...)
        yield self.ok
        yield self.outputs


# ---------------------------------------------------------------------------
# PipelineElement
# ---------------------------------------------------------------------------

class PipelineElement(Actor):
    """One stage of a pipeline.  Subclasses implement process_frame and may
    implement start_stream / stop_stream (reference: pipeline.py:270-338).

    Elements whose compute is a jax program should build/jit it once in
    __init__ or start_stream and call it in process_frame — process_frame
    itself is host-side control code.

    Subclasses may declare a class-level `contracts` dict (io name →
    contract string, "in:"/"out:" prefixes for direction-specific ones)
    that the static checker uses when the pipeline definition doesn't
    declare its own — resolved by import only, never construction."""

    contracts: dict = {}

    def __init__(self, runtime, name, definition: PipelineElementDefinition,
                 pipeline: "Pipeline | None" = None, protocol=None,
                 tags=None):
        share = {"element": definition.name,
                 "inputs": ",".join(definition.input_names),
                 "outputs": ",".join(definition.output_names)}
        super().__init__(runtime, name,
                         protocol or ServiceProtocol("pipeline_element"),
                         tags, share=share)
        self.definition = definition
        self.pipeline = pipeline
        for key, value in definition.parameters.items():
            self.ec_producer.update(f"parameter.{key}", value)

    # -- parameters: stream > element > pipeline (reference: :316-329) ------
    def get_parameter(self, name: str, default=None, stream: Stream = None):
        if stream is not None:
            # specific beats general at every level
            scoped = f"{self.definition.name}.{name}"
            if scoped in stream.parameters:
                return stream.parameters[scoped], True
            if name in stream.parameters:
                return stream.parameters[name], True
        if name in self.definition.parameters:
            return self.definition.parameters[name], True
        if self.pipeline is not None:
            pipeline_params = self.pipeline.definition.parameters
            # specific beats general: "{element}.{name}" before bare "{name}"
            scoped = f"{self.definition.name}.{name}"
            if scoped in pipeline_params:
                return pipeline_params[scoped], True
            if name in pipeline_params:
                return pipeline_params[name], True
        return default, False

    # -- stream lifecycle ---------------------------------------------------
    def start_stream(self, stream: Stream) -> None:
        pass

    def stop_stream(self, stream: Stream) -> None:
        pass

    def process_frame(self, frame: Frame, **inputs) -> FrameOutput:
        raise NotImplementedError

    # -- source API: push a new frame into the owning pipeline --------------
    def create_frame(self, stream: Stream, swag: dict) -> None:
        """Thread-safe: posts a process_frame message onto the pipeline's
        mailbox (reference: pipeline.py:415-416)."""
        if self.pipeline is not None:
            self.pipeline.post("process_frame", stream.stream_id, swag)


class _RemoteElementPlaceholder:
    """Stands in for a remote element until discovery finds it
    (reference: PipelineElementRemoteAbsent, pipeline.py:340-352).

    Also holds the hop's coalescing state: frames bound for this
    destination buffer here and flush as ONE envelope when the consumer
    is behind (outstanding replies > 0), amortizing per-message wire
    overhead across the burst.

    `candidates` keeps EVERY currently-discovered matching service (in
    discovery order), not just the active one: when the active proxy
    leaves — or a hop times out against it — the pipeline fails over to
    the next candidate instead of erroring frames.  Values are each
    candidate's advertised peer-endpoint tag (None when the service has
    no peer data plane), consumed by Pipeline._negotiate_peer."""

    def __init__(self, definition: PipelineElementDefinition):
        self.definition = definition
        self.proxy = None
        self.topic_path = None
        # topic_path -> peer endpoint tag value | None
        self.candidates: dict[str, str | None] = {}
        # topic_path -> advertised serving role ("prefill" / "decode" /
        # "colocated" / "" when untagged) — ISSUE 14: the registrar
        # record's role tag, consumed by role-aware candidate rotation
        # (Pipeline._rotate_candidate: a service filter loose enough
        # to match several roles must not fail a decode hop over onto
        # a prefill runtime)
        self.roles: dict[str, str] = {}
        self.buffer: list = []          # (entry, one_way) pending sends
        self.outstanding = 0            # request/response hops in flight
        self.flush_scheduled = False

    @property
    def found(self) -> bool:
        return self.proxy is not None


@dataclass
class _PendingHop:
    """One outstanding request/response remote hop.  The single source
    of truth for everything the recovery machinery needs: the frame to
    resume, retry budget spent, whether a request copy is currently in
    flight, and the timers (timeout lease + scheduled resend) that MUST
    be cancelled on every exit path — reply, expiry, failover redirect,
    stream destruction — so dead hops never fire expired handlers."""
    frame: Frame
    node_name: str
    inputs: dict
    lease: Lease | None = None
    attempts: int = 0               # retries consumed
    sent: bool = False              # a request copy is in flight
    sent_to: str | None = None      # candidate the last copy shipped to
    resend_timer: int | None = None
    # the hop's child trace context (trace id + inherited deadline);
    # every attempt's wire copy carries it, retries re-serialize it with
    # the SHRUNK remaining budget
    trace: "tracing.TraceContext | None" = None
    hop_started: float = 0.0        # perf_counter at hop creation
    attempt_started: float = 0.0    # perf_counter at last wire send

    def cancel(self, engine) -> None:
        if self.lease is not None:
            self.lease.cancel()
            self.lease = None
        if self.resend_timer is not None:
            engine.remove_timer_handler(self.resend_timer)
            self.resend_timer = None


_RETIRED_HOP_CAP = 2048     # recently settled hop ids (reply dedup)
_SERVED_HOP_CAP = 1024      # serving-side request dedup + reply replay
_SERVED_REPLY_CACHE_BYTES = 1 << 18   # replies above this aren't cached
_SERVED_REPLY_BUDGET_BYTES = 8 << 20  # aggregate pin across ALL entries
# per-tenant sub-budget (ISSUE 10): one flooding tenant's replies must
# not evict every other tenant's replay capacity — a TAGGED tenant over
# this pin demotes ITS OWN oldest replies to dedup-only first, before
# the aggregate budget touches anyone else's.  Untagged traffic ("")
# is exempt: it has no neighbours to be fair to, and capping it would
# silently shrink the PR 4 aggregate semantics for untenanted serving.
_SERVED_REPLY_TENANT_BUDGET_BYTES = 2 << 20


def _payload_nbytes(value) -> int:
    """Tensor/bytes weight of a reply payload (nested containers
    included) — the replay cache must not pin up to _SERVED_HOP_CAP
    full-size image replies in memory."""
    import numpy as np
    if isinstance(value, np.ndarray):
        return value.nbytes
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    if isinstance(value, dict):
        return sum(_payload_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_payload_nbytes(v) for v in value)
    return 0


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------

class Pipeline(PipelineElement):
    """The pipeline engine.  A Pipeline is-a PipelineElement, so pipelines
    nest (reference: pipeline.py:377-398).

    Frame walk: topological DAG order; each element's declared inputs are
    gathered from the swag (applying fan-in renames), process_frame invoked,
    outputs renamed per fan-out mapping and merged back into the swag, and
    per-element wall time recorded (reference hot loop: pipeline.py:623-715).
    """

    def __init__(self, runtime, definition: PipelineDefinition,
                 name: str | None = None, definition_pathname: str = "",
                 element_classes: dict | None = None,
                 services_cache: ServicesCache | None = None,
                 stream_lease_time: float = STREAM_LEASE_TIME,
                 auto_create_streams: bool = False,
                 remote_timeout: float = 30.0,
                 coalesce_frames: int = 16,
                 remote_wire_codecs: dict | None = None,
                 remote_retries: int = 0,
                 remote_backoff: float = 0.25,
                 remote_backoff_max: float = 4.0,
                 retry_jitter: float = 0.25,
                 retry_seed: int | None = None,
                 stream_failure_budget: int = 1,
                 frame_deadline: float = 0.0,
                 admission=None):
        self._element_classes = element_classes or {}
        self.graph = PipelineGraph.from_definition(definition)
        self.graph.validate(definition)
        super().__init__(
            runtime, name or definition.name,
            PipelineElementDefinition(name=definition.name),
            pipeline=None, protocol=PROTOCOL_PIPELINE,
            tags=[f"definition={definition_pathname}"] if definition_pathname
                 else None)
        # the Actor base stored the element-level definition; a Pipeline's
        # own definition is the pipeline-level one (it has .parameters too,
        # so get_parameter's fallback chain terminates here)
        self.element_definition = self.definition
        self.definition = definition
        self.pipeline = self        # parameter resolution terminates here
        self.logger = get_logger(f"pipeline.{self.name}")
        self.stream_lease_time = stream_lease_time
        self.auto_create_streams = auto_create_streams
        self.streams: dict[str, Stream] = {}
        self._remote: dict[str, _RemoteElementPlaceholder] = {}
        self._services_cache = services_cache
        self._frame_handlers: list[Callable] = []
        # outstanding request/response remote hops: hop_id → (frame,
        # node_name, timeout lease)
        self.remote_timeout = remote_timeout
        self._pending_remote: dict[str, _PendingHop] = {}
        self._hop_counter = itertools.count(1)
        # incarnation nonce: hop ids must not collide across pipeline
        # rebuilds that reuse the same reply topic (embedded runtime
        # re-creation, OS pid reuse), or the serving dedup ring would
        # answer a NEW caller's hop 'name.1' with a replay of the OLD
        # incarnation's cached reply
        self._hop_nonce = uuid.uuid4().hex[:8]
        # -- failure recovery (ISSUE 4) ----------------------------------
        # remote_retries > 0 turns the recovery machinery ON: hop
        # timeouts retry with exponential backoff + seeded jitter,
        # candidate rotation tries OTHER discovered services, absent
        # placeholders buffer frames until discovery re-resolves, and
        # proxy loss redirects in-flight hops to the replacement.  The
        # default (0) keeps the legacy fail-fast semantics.
        self.remote_retries = max(0, int(remote_retries))
        self.remote_backoff = float(remote_backoff)
        self.remote_backoff_max = float(remote_backoff_max)
        self.retry_jitter = float(retry_jitter)
        # retry_seed=None spreads the jitter for real (a fleet of
        # pipelines must not retry in lockstep); seed it for tests
        self._retry_rng = random.Random(retry_seed)
        # stream_failure_budget consecutive frame failures stop a stream
        # (1 = legacy: first failure destroys it)
        self.stream_failure_budget = max(1, int(stream_failure_budget))
        # frame_deadline > 0 stamps every NEW frame with an end-to-end
        # deadline (engine-clock seconds): remote hops propagate it,
        # retry backoff is clamped to what remains, and an exhausted
        # budget fails the frame fast — charged to the stream failure
        # budget like any other frame failure (ISSUE 5)
        self.frame_deadline = max(0.0, float(frame_deadline))
        # ad-hoc dict preserved for existing readers; increments mirror
        # into the process-wide metrics registry (observe/metrics.py)
        self.recovery_stats = MirroredStats({
            "retries": 0, "failovers": 0, "dup_replies": 0,
            "dup_requests": 0, "replayed_replies": 0,
            "frames_failed": 0, "streams_stopped": 0,
            "one_way_shed": 0, "deadline_exceeded": 0,
            "deadline_rejected": 0, "shed_early": 0,
            "admission_shed": 0,
        }, metric="pipeline_recovery_total",
            help="pipeline recovery machinery events by kind",
            labels={"pipeline": self.name})
        registry = default_registry()
        wire_help = "wire envelopes shipped by the remote-hop data plane"
        self._wire_counters = {
            "request_envelopes": registry.counter(
                "pipeline_wire_envelopes_total", wire_help,
                labels={"pipeline": self.name, "direction": "request"}),
            "request_frames": registry.counter(
                "pipeline_wire_frames_total",
                "frames carried inside wire envelopes",
                labels={"pipeline": self.name, "direction": "request"}),
            "reply_envelopes": registry.counter(
                "pipeline_wire_envelopes_total", wire_help,
                labels={"pipeline": self.name, "direction": "reply"}),
            "reply_frames": registry.counter(
                "pipeline_wire_frames_total",
                "frames carried inside wire envelopes",
                labels={"pipeline": self.name, "direction": "reply"}),
        }
        self._hop_seconds = registry.histogram(
            "pipeline_hop_seconds",
            "remote request/response hop latency (send to reply)",
            labels={"pipeline": self.name})
        self._retired_hops: dict[str, bool] = {}    # reply dedup ring
        self._served_hops: dict = {}    # (reply_topic, hop_id) -> reply
        self._served_reply_bytes = 0    # aggregate pinned reply payload
        self._served_reply_tenant_bytes: dict[str, int] = {}
        # remote-hop wire tuning: coalesce_frames bounds how many frames
        # one envelope may carry (1 disables); codec hints opt named
        # swag keys into lossy wire codecs (transport/wire.py)
        self.coalesce_frames = max(1, int(coalesce_frames))
        self._remote_wire_codecs = dict(remote_wire_codecs or {})
        self._reply_buffer: dict[str, list] = {}
        self._reply_flush_scheduled = False
        # -- overload control (ISSUE 9) ----------------------------------
        # admission is an ops/admission.py AdmissionGate: remote
        # requests whose deadline budget cannot survive the estimated
        # queue wait are answered shed-early BEFORE any work, and
        # admitted requests pass a per-tenant weighted fair queue whose
        # inflight window is credited back as replies go out.  None
        # keeps the legacy walk-immediately semantics.
        self.admission = admission
        self._admitted_keys: set = set()
        self._admission_timer = None
        if admission is not None:
            # drain BACKSTOP only: the hot-path trigger is a reply
            # releasing an inflight credit (zero-delay oneshot in
            # _send_remote_reply); this timer exists so a queued frame
            # cannot strand when the pipeline goes idle, so it ticks
            # slowly and exits immediately on an empty queue
            self._admission_timer = runtime.event.add_timer_handler(
                self._drain_admission, 0.05)
            # give the fair queue this runtime's engine clock (unless
            # the builder provided one) so every drained frame observes
            # its MEASURED dwell into admission_queue_wait_seconds —
            # the number request journeys carry (ISSUE 12)
            admission.queue.set_clock(runtime.event.clock.now)
        self._create_elements()
        self._precompute_schedule()
        self.ec_producer.update("element_count", len(self.graph))
        self.ec_producer.update("stream_count", 0)

    # -- element construction (reference: pipeline.py:429-493) --------------
    def _create_elements(self) -> None:
        for node in self.graph.nodes():
            element_def = self.definition.element(node.name)
            if element_def.is_remote:
                placeholder = _RemoteElementPlaceholder(element_def)
                node.element = placeholder
                self._remote[node.name] = placeholder
                self._watch_remote(node.name, element_def)
                continue
            node.element = self._instantiate(element_def)

    def _instantiate(self, element_def) -> PipelineElement:
        local = element_def.deploy.get("local", {})
        class_name = local.get("class_name", element_def.name)
        if class_name in self._element_classes:
            element_class = self._element_classes[class_name]
        elif "module" in local:
            element_class = load_class(local["module"], class_name)
        else:
            from . import elements as _builtin
            element_class = getattr(_builtin, class_name, None)
            if element_class is None:
                raise PipelineError(
                    f"element {element_def.name}: class {class_name} not in "
                    f"element_classes, no deploy.local.module given, and not "
                    f"a built-in element")
        return element_class(self.runtime, f"{self.name}.{element_def.name}",
                             element_def, pipeline=self)

    def _precompute_schedule(self) -> None:
        """Freeze the per-frame walk: graph + definition are immutable after
        construction, so topo order, predecessor/rename maps and element
        definitions are computed once, not per frame (the reference rebuilds
        them each frame inside its hot loop, pipeline.py:650-712)."""
        self._topo_nodes = self.graph.topological_order()
        preds = self.graph.predecessor_map()
        self._element_defs = {node.name: self.definition.element(node.name)
                              for node in self._topo_nodes}
        # per-node: declared input name -> name as produced upstream
        self._renames: dict[str, dict[str, str]] = {}
        for node in self._topo_nodes:
            rename = {}
            for pred in preds[node.name]:
                mapping = self.graph.mappings.get((pred, node.name), {})
                for src, dst in mapping.items():
                    rename[dst] = src
            self._renames[node.name] = rename

    @property
    def _recovery_enabled(self) -> bool:
        return self.remote_retries > 0

    @property
    def _peer_host(self):
        """The runtime's peer data plane, when enabled (ISSUE 6)."""
        return getattr(self.runtime, "peer", None)

    def _negotiate_peer(self, topic_path: str) -> None:
        """Open a direct data-plane channel to the service at
        `topic_path` when both sides speak peer: our requests to its
        /in topic and its replies to our topic_in pin to the channel.
        No-op (broker path stays) when either side lacks an endpoint —
        and on refusal/death the PeerHost falls back by itself."""
        host = self._peer_host
        if host is None:
            return
        endpoint = None
        for placeholder in self._remote.values():
            if topic_path in placeholder.candidates:
                endpoint = placeholder.candidates[topic_path]
                break
        if not endpoint:
            return
        try:
            host.negotiate(topic_path, endpoint,
                           pin_topics=[f"{topic_path}/in"],
                           reply_topics=[self.topic_in])
        except Exception:
            # a broken advertisement must not abort _activate_remote —
            # the failover redirect and buffered-frame flush that
            # follow it are correctness, the peer channel is only an
            # optimization
            self.logger.exception(
                "pipeline %s: peer negotiation with %s failed; "
                "staying on the broker path", self.name, topic_path)

    def _watch_remote(self, node_name: str, element_def) -> None:
        """Swap the placeholder for a live proxy when the remote pipeline
        service appears (reference: pipeline.py:591-620).  Every matching
        service is tracked as a candidate; losing the active one fails
        over to the next instead of going absent."""
        if self._services_cache is None:
            return
        raw = element_def.deploy["remote"]["service_filter"]
        service_filter = ServiceFilter(**raw) if isinstance(raw, dict) \
            else raw

        def handler(command, fields):
            placeholder = self._remote[node_name]
            if command == "add":
                # candidates map topic_path → advertised peer endpoint
                # tag (None when the service has no peer data plane);
                # the role tag (ISSUE 14) rides the same record
                tags = ServiceTags.to_dict(fields.tags)
                endpoint = tags.get("peer")
                placeholder.candidates[fields.topic_path] = endpoint
                placeholder.roles[fields.topic_path] = \
                    tags.get("role", "")
                if not placeholder.found:
                    self._activate_remote(node_name, fields.topic_path)
                elif placeholder.topic_path == fields.topic_path:
                    # re-registration of the ACTIVE service (fresh
                    # incarnation, peer enabled late): re-negotiate the
                    # data plane with the current endpoint facts
                    self._negotiate_peer(fields.topic_path)
            elif command == "remove":
                placeholder.candidates.pop(fields.topic_path, None)
                placeholder.roles.pop(fields.topic_path, None)
                if self._peer_host is not None:
                    # the service left: its channel (if any) is a
                    # corpse — unpin so traffic rides the broker to
                    # whatever candidate activation picks next
                    self._peer_host.release(f"{fields.topic_path}/in")
                if placeholder.topic_path == fields.topic_path:
                    placeholder.proxy = None
                    placeholder.topic_path = None
                    if placeholder.candidates:
                        self._activate_remote(
                            node_name, next(iter(placeholder.candidates)),
                            failover=True,
                            redirect=self._recovery_enabled)

        self._services_cache.add_handler(handler, service_filter)

    def _activate_remote(self, node_name: str, topic_path: str,
                         failover: bool = False,
                         redirect: bool = False) -> None:
        """Point a remote node at `topic_path` and, on a failover with
        recovery enabled, redirect in-flight and buffered hops to the new
        proxy (duplicate replies from the old one dedup on hop id)."""
        placeholder = self._remote[node_name]
        placeholder.topic_path = topic_path
        placeholder.proxy = get_remote_proxy(
            self.runtime, f"{topic_path}/in", Pipeline,
            codec_hints=self._remote_wire_codecs)
        # peer data plane (ISSUE 6): first hop to a discovered proxy
        # negotiates a direct channel through the control plane; data
        # envelopes pin to it, with the broker as the standing fallback
        self._negotiate_peer(topic_path)
        if failover:
            self.recovery_stats["failovers"] += 1
            self.logger.warning(
                "pipeline %s: remote element %s failed over to %s",
                self.name, node_name, topic_path)
        else:
            self.logger.info("pipeline %s: remote element %s found at %s",
                             self.name, node_name, topic_path)
        if redirect:
            for hop_id, pending in list(self._pending_remote.items()):
                if pending.node_name == node_name and pending.sent:
                    self._resend_hop(hop_id)
        if placeholder.buffer:
            self._flush_remote(placeholder)

    def remote_elements_ready(self) -> bool:
        return all(p.found for p in self._remote.values())

    # -- stream lifecycle (reference: pipeline.py:717-749) ------------------
    def create_stream(self, stream_id, parameters: dict | None = None,
                      lease_time: float | None = None) -> Stream:
        stream_id = str(stream_id)
        if stream_id in self.streams:
            raise PipelineError(f"stream exists: {stream_id}")
        stream = Stream(stream_id=stream_id,
                        parameters=dict(parameters or {}))
        lease_time = lease_time if lease_time is not None \
            else self.stream_lease_time
        if lease_time > 0:
            stream.lease = Lease(
                self.runtime.event, lease_time, stream_id,
                lease_expired_handler=lambda _id:
                    self.destroy_stream(stream_id))
        self.streams[stream_id] = stream
        self.ec_producer.update("stream_count", len(self.streams))
        try:
            for node in self._topo_nodes:
                element = node.element
                if isinstance(element, PipelineElement):
                    element.start_stream(stream)
        except Exception as exc:
            # don't leave a half-initialized stream registered
            self.destroy_stream(stream_id)
            raise PipelineError(
                f"pipeline {self.name}: start_stream({stream_id}) failed in "
                f"element {node.name}: {exc!r}") from exc
        return stream

    def destroy_stream(self, stream_id) -> None:
        stream = self.streams.pop(str(stream_id), None)
        if stream is None:
            return
        stream.state = "stop"
        if stream.lease is not None:
            stream.lease.cancel()
        # retire every remote hop the stream still has pending: cancel
        # its timeout lease and any scheduled resend, so a dead hop can
        # never fire an expired handler into a destroyed stream
        for hop_id, pending in list(self._pending_remote.items()):
            if pending.frame.stream is stream:
                self._pending_remote.pop(hop_id, None)
                pending.cancel(self.runtime.event)
                self._retire_hop(hop_id)
                self._purge_buffered_hop(pending.node_name, hop_id)
                if pending.sent:
                    self._hop_settled(pending.node_name)
        # answer remote callers of frames still parked DEFERRED: without
        # a reply the caller's serving-side dedup entry stays "in
        # progress" forever and every retry of the hop id is skipped —
        # the failure reply below is cached, so retries replay it
        parked, stream.parked = stream.parked, []
        for frame in parked:
            if frame.reply_to is not None:
                self._send_remote_reply(
                    frame, False,
                    {"diagnostic": stream.last_diagnostic
                     or "stream destroyed while frame deferred",
                     "stream_stopped": True})
        for node in self._topo_nodes:
            element = node.element
            if isinstance(element, PipelineElement):
                try:
                    element.stop_stream(stream)
                except Exception:
                    self.logger.exception(
                        "pipeline %s: %s.stop_stream(%s) raised", self.name,
                        node.name, stream_id)
        self.ec_producer.update("stream_count", len(self.streams))

    def add_frame_handler(self, handler: Callable) -> None:
        """handler(frame) after every completed frame (tests, sinks,
        benchmark harnesses)."""
        self._frame_handlers.append(handler)

    # -- frame engine (reference hot loop: pipeline.py:623-715) -------------
    def process_frame(self, frame_or_stream_id, swag: dict | None = None,
                      _reply_to: tuple | None = None,
                      _reply_skip: dict | None = None,
                      **_kwargs) -> FrameOutput:
        """Dual interface: called with (Frame, **inputs) when nested as an
        element, or with (stream_id, swag) via the actor mailbox.
        _reply_to (internal, set by process_frame_remote): address the
        final swag back to a remote caller when the walk completes."""
        if isinstance(frame_or_stream_id, Frame):
            # nested as an element: isolate the walk on a swag copy so a
            # nested failure or scratch value never mutates the parent frame;
            # the declared-output filter below returns only our interface
            parent = frame_or_stream_id
            stream = parent.stream
            child_swag = dict(parent.swag)
            child_swag.update(_kwargs)      # fan-in renamed inputs
            frame = Frame(stream=stream, frame_id=parent.frame_id,
                          swag=child_swag, metrics=parent.metrics,
                          trace=parent.trace)
        else:
            stream = self.streams.get(str(frame_or_stream_id))
            if stream is None:
                # "*" always auto-creates; named streams only when serving
                # remote frames (auto_create_streams) — leased, so orphaned
                # remote streams expire
                if str(frame_or_stream_id) == DEFAULT_STREAM_ID:
                    stream = self.create_stream(DEFAULT_STREAM_ID,
                                                lease_time=0)
                elif self.auto_create_streams:
                    stream = self.create_stream(str(frame_or_stream_id))
                else:
                    self.logger.warning("pipeline %s: frame for unknown "
                                        "stream %s dropped", self.name,
                                        frame_or_stream_id)
                    return FrameOutput(False, diagnostic="unknown stream")
            # trace context: a remote frame arrives under its caller's
            # activated context (process_frame_remote / the actor
            # dispatch); a locally-sourced frame mints a fresh root —
            # with this pipeline's end-to-end deadline when configured
            context = tracing.current_trace()
            if context is None and (self.frame_deadline > 0
                                    or tracing.tracer.enabled):
                deadline = None
                if self.frame_deadline > 0:
                    deadline = self.runtime.event.clock.now() + \
                        self.frame_deadline
                context = tracing.new_trace(deadline=deadline)
            frame = Frame(stream=stream, frame_id=stream.next_frame_id(),
                          swag=dict(swag or {}), reply_to=_reply_to,
                          reply_skip=_reply_skip, trace=context)
        if stream.lease is not None:
            stream.lease.extend()

        frame.metrics["time_pipeline_start"] = time.perf_counter()
        # the walk runs under the frame's trace context: elements,
        # nested pipelines, remote proxies (envelope headers) and
        # TraceCollector leaves all inherit it ambiently
        with tracing.activate(frame.trace):
            return self._walk(frame, 0)

    def resume_frame(self, frame: Frame, node_name: str,
                     outputs: dict | None) -> FrameOutput:
        """Continue a frame parked by a DEFERRED element (continuous
        batching: the element submitted work to a scheduler and calls this
        — typically via `pipeline.post("resume_frame", ...)` — when the
        batch completes)."""
        if frame.stream.state == "stop":
            # the stream died while the frame was parked (failure budget,
            # lease expiry, shutdown): drop the resume quietly — a remote
            # caller was already answered by destroy_stream
            return FrameOutput(False, diagnostic="stream stopped")
        if frame in frame.stream.parked:
            frame.stream.parked.remove(frame)
        index = frame.deferred_at
        if index is None:
            return FrameOutput(False, diagnostic="frame not deferred")
        node = self._topo_nodes[index]
        if node.name != node_name:
            return FrameOutput(
                False, diagnostic=f"deferred at {node.name}, "
                                  f"resumed as {node_name}")
        frame.deferred_at = None
        frame.metrics[f"time_{node.name}"] = \
            time.perf_counter() - frame.deferred_since
        # the deferred element's span covers park → resume (the wait IS
        # where the frame's budget went: batch formation + device time)
        self._record_call_span(node_name, frame, frame.deferred_since,
                               frame.metrics[f"time_{node.name}"],
                               deferred=True)
        if isinstance(outputs, Exception):
            self._fail_frame(frame, node.name, repr(outputs))
            return FrameOutput(False,
                               diagnostic=f"{node.name}: {outputs!r}")
        if outputs:
            self._merge_outputs(node, self._element_defs[node.name],
                                outputs, frame.swag)
        with tracing.activate(frame.trace):
            return self._walk(frame, index + 1)

    def _record_call_span(self, node_name: str, frame: Frame,
                          started: float, duration: float,
                          deferred: bool = False) -> None:
        """Per-element span under the frame's trace (ISSUE 10 satellite
        closing the PR 5 follow-up): Perfetto dumps show where a
        frame's budget went element by element, not just per hop."""
        trc = tracing.tracer
        if not trc.enabled or frame.trace is None:
            return
        args = {"stream": frame.stream.stream_id,
                "frame": frame.frame_id}
        if deferred:
            args["deferred"] = True
        trc.record(f"call:{node_name}", started, duration,
                   context=frame.trace, cat="element", proc=self.name,
                   span_id=tracing.new_span_id(), args=args)

    def _walk(self, frame: Frame, start_index: int) -> FrameOutput:
        swag = frame.swag
        for index in range(start_index, len(self._topo_nodes)):
            node = self._topo_nodes[index]
            element = node.element
            element_def = self._element_defs[node.name]
            inputs = self._gather_inputs(node.name, element_def, swag)
            if inputs is None:
                self._fail_frame(frame, node.name,
                                 "missing inputs in swag")
                return FrameOutput(False,
                                   diagnostic=f"{node.name}: missing inputs")
            element_start = time.perf_counter()

            diagnostic = ""
            if isinstance(element, _RemoteElementPlaceholder):
                ok, outputs = self._process_remote(element, frame,
                                                   inputs, node.name)
                if not ok:
                    diagnostic = outputs if isinstance(outputs, str) \
                        else "remote element absent"
                    outputs = None
            else:
                try:
                    result = element.process_frame(frame, **inputs)
                except Exception as exc:
                    self.logger.exception(
                        "pipeline %s: element %s raised", self.name,
                        node.name)
                    self._fail_frame(frame, node.name, repr(exc))
                    return FrameOutput(False,
                                       diagnostic=f"{node.name}: {exc!r}")
                ok, outputs = result
                diagnostic = getattr(result, "diagnostic", "")
            if ok and outputs is DEFERRED:
                # park the frame; the element resumes it asynchronously.
                # The stream remembers it so destroy_stream can answer
                # its remote caller instead of leaving the hop hanging
                frame.deferred_at = index
                frame.deferred_since = element_start
                frame.stream.parked.append(frame)
                return FrameOutput(True, DEFERRED)
            frame.metrics[f"time_{node.name}"] = \
                time.perf_counter() - element_start
            self._record_call_span(node.name, frame, element_start,
                                   frame.metrics[f"time_{node.name}"])
            if not ok:
                diagnostic = diagnostic or "element reported not-ok"
                self._fail_frame(frame, node.name, diagnostic)
                return FrameOutput(
                    False, diagnostic=f"{node.name}: {diagnostic}")
            if outputs:
                self._merge_outputs(node, element_def, outputs, swag)

        frame.metrics["time_pipeline"] = \
            time.perf_counter() - frame.metrics["time_pipeline_start"]
        if self.streams.get(frame.stream.stream_id) is frame.stream:
            # the budget counts whole FRAMES on streams this pipeline
            # owns: a nested element's success mid-frame must not erase
            # the parent stream's run of frame failures
            frame.stream.consecutive_failures = 0
        for handler in self._frame_handlers:
            handler(frame)
        if frame.reply_to is not None:
            self._send_remote_reply(frame, True, swag)
        return FrameOutput(True, dict(swag))

    def _merge_outputs(self, node, element_def, outputs, swag) -> None:
        # an element's interface is its declared outputs: scratch values
        # (e.g. a nested pipeline's intermediates) don't leak
        if element_def.output:
            declared = element_def.output_names
            outputs = {k: v for k, v in outputs.items() if k in declared}
        self._scatter_outputs(node.name, outputs, swag)

    def _gather_inputs(self, node_name, element_def, swag):
        """Collect declared inputs from the swag, applying fan-in renames
        (reference: pipeline.py:657-675)."""
        rename = self._renames[node_name]
        inputs = {}
        for input_name in element_def.input_names:
            source_name = input_name if input_name in swag else \
                rename.get(input_name, input_name)
            if input_name in swag:
                inputs[input_name] = swag[input_name]
            elif source_name in swag:
                inputs[input_name] = swag[source_name]
            else:
                return None
        return inputs

    def _scatter_outputs(self, node_name, outputs, swag) -> None:
        """Merge outputs into the swag, applying fan-out renames per edge
        mapping (reference: pipeline.py:687-703)."""
        renamed = dict(outputs)
        for successor in self.graph.successors(node_name):
            mapping = self.graph.mappings.get((node_name, successor), {})
            for src, dst in mapping.items():
                if src in outputs:
                    renamed[dst] = outputs[src]
        swag.update(renamed)

    def _process_remote(self, placeholder, frame, inputs, node_name):
        """Ship a frame to a discovered remote pipeline.

        Result semantics (this framework's contract — the reference's hop
        is fire-and-forget with result return an acknowledged TODO,
        reference pipeline.py:693-695):

        * remote node declares NO outputs → one-way: publish and continue
          the walk (sink semantics, e.g. remote recorder/speaker);
        * remote node declares outputs → request/response: the frame
          DEFERS here, the serving pipeline walks its own graph and
          replies with its final swag to our topic_in
          (resume_remote_frame), which resumes the walk with the declared
          outputs merged; a lease fails the frame if no reply arrives
          within remote_timeout.

        The serving pipeline should run with auto_create_streams=True so
        frames for upstream-created streams are accepted.  On a
        binary-capable transport, tensor values cross inside the binary
        wire envelope (transport/wire.py) — zero text round-trip, with
        optional per-key codecs (remote_wire_codecs) — and bursts of
        frames bound for the same destination coalesce into one
        envelope.  On text-only transports the legacy S-expression path
        applies: tensors must pass through PE_DataEncode before the
        boundary and PE_DataDecode after it (the device data plane
        bypasses this entirely for co-located elements).

        With recovery enabled (remote_retries > 0) an ABSENT placeholder
        no longer fails the frame: the hop buffers (bounded for one-way
        sinks, lease-governed for request/response) and flushes when
        discovery re-resolves the service."""
        element_def = self._element_defs[node_name]
        if not element_def.output:
            if placeholder.found:
                self._queue_remote(placeholder,
                                   [frame.stream_id, inputs], one_way=True)
            elif self._recovery_enabled:
                self._buffer_entry(placeholder,
                                   [frame.stream_id, inputs], one_way=True)
            else:
                return False, None
            return True, {}
        if not placeholder.found and not self._recovery_enabled:
            return False, None
        # hop trace context: child of the frame's context, inheriting
        # the end-to-end deadline.  A frame whose budget is ALREADY
        # spent fails fast here — no send, no retry, the failure
        # charged to the stream budget like any other frame failure
        hop_trace = frame.trace.child() if frame.trace is not None \
            else None
        now = self.runtime.event.clock.now()
        if hop_trace is not None and hop_trace.expired(now):
            self.recovery_stats["deadline_exceeded"] += 1
            return False, (f"deadline exceeded before remote hop "
                           f"{node_name} (budget spent "
                           f"{-hop_trace.remaining(now):.3f}s ago)")
        hop_id = (f"{self.name}.{self._hop_nonce}"
                  f".{next(self._hop_counter)}")
        # keep the sent inputs: the serving side elides identity
        # passthroughs from its reply (no point echoing the payload),
        # so the resume re-merges them from here when declared
        pending = _PendingHop(frame=frame, node_name=node_name,
                              inputs=inputs, trace=hop_trace,
                              hop_started=time.perf_counter())
        self._pending_remote[hop_id] = pending
        self._arm_hop_lease(pending, hop_id)
        entry = self._hop_entry(pending, hop_id)
        if placeholder.found:
            self._queue_remote(placeholder, entry, one_way=False)
        else:
            # awaiting discovery: the lease bounds the wait
            self._buffer_entry(placeholder, entry, one_way=False)
        return True, DEFERRED

    def _hop_entry(self, pending: _PendingHop, hop_id: str) -> list:
        """The wire entry for one request hop.  The trace context is
        re-serialized per send, so a retry carries the SHRUNK remaining
        budget, not the original one.  The stream's tenant/tier
        parameters ride as a trailing self-tagged field list (ISSUE 9)
        — the serving admission gate charges the hop to the right
        per-tenant budget; both fields are markers, so a tenant tag
        without a trace is unambiguous at the receiver."""
        entry = [pending.frame.stream_id, pending.inputs, self.topic_in,
                 hop_id]
        if pending.trace is not None:
            entry.append(pending.trace.to_fields(
                self.runtime.event.clock.now()))
        parameters = pending.frame.stream.parameters
        tenant = parameters.get("tenant")
        if tenant:
            entry.append(wire.tenant_fields(tenant,
                                            parameters.get("tier", 1)))
        return entry

    def _arm_hop_lease(self, pending: _PendingHop, hop_id: str) -> None:
        if pending.lease is not None:
            pending.lease.cancel()
        timeout = self.remote_timeout
        if pending.trace is not None:
            remaining = pending.trace.remaining(
                self.runtime.event.clock.now())
            if remaining is not None:
                # the timeout lease never outlives the frame's deadline:
                # a hop with 0.3 s of budget left times out (and gets
                # its fail-fast verdict) at 0.3 s, not remote_timeout
                timeout = max(0.01, min(timeout, remaining))
        pending.lease = Lease(
            self.runtime.event, timeout, hop_id,
            lease_expired_handler=self._remote_hop_expired)

    def _purge_buffered_hop(self, node_name: str, hop_id: str) -> None:
        """Drop a retired hop's still-buffered request entry — request
        hops escape the one-way shed cap (they are lease-governed), so
        every pop path of _pending_remote must also purge here or an
        absent placeholder's buffer grows without bound over a long
        outage."""
        placeholder = self._remote.get(node_name)
        if placeholder is None:
            return
        placeholder.buffer = [(e, ow) for e, ow in placeholder.buffer
                              if ow or e[3] != hop_id]

    def _buffer_entry(self, placeholder, entry, one_way: bool) -> None:
        """Park a hop for an absent destination.  One-way (sink) entries
        have no lease watching them, so their OWN share of the buffer is
        bounded: past the cap the oldest one-way entry is shed (request
        hops don't count against it — they are lease-governed)."""
        placeholder.buffer.append((entry, one_way))
        cap = max(4 * self.coalesce_frames, 64)
        if one_way and sum(
                1 for _, ow in placeholder.buffer if ow) > cap:
            for index, (_, buffered_one_way) in \
                    enumerate(placeholder.buffer):
                if buffered_one_way:
                    del placeholder.buffer[index]
                    break
            # shed loss must stay observable: soaks and production both
            # read recovery_stats to account for every frame
            self.recovery_stats["one_way_shed"] += 1
            self.logger.debug(
                "pipeline %s: absent remote sink over buffer cap %d; "
                "oldest one-way frame shed", self.name, cap)

    # -- remote-hop coalescing ----------------------------------------------
    # Per-destination send buffer: an idle link (no outstanding replies)
    # flushes immediately, so a lone frame pays no added latency; while
    # the consumer is behind, frames accumulate and flush as ONE
    # envelope when the buffer fills, a reply arrives (ack-clocked), or
    # the next event-engine turn begins — per-message publish/parse/
    # mailbox overhead amortizes across the burst.  Coalescing requires
    # the binary envelope, so text-only transports keep per-frame sends.

    def _queue_remote(self, placeholder, entry, one_way: bool) -> None:
        if self.coalesce_frames <= 1 or \
                not wire.supports_binary(self.runtime.message):
            self._send_remote([(entry, one_way)], placeholder)
            return
        placeholder.buffer.append((entry, one_way))
        if len(placeholder.buffer) >= self.coalesce_frames:
            self._flush_remote(placeholder)
            return
        if not one_way and placeholder.outstanding == 0:
            self._flush_remote(placeholder)
            return
        if one_way and not placeholder.flush_scheduled:
            # idle link (no coalescing window open): ship this frame
            # now — a lone fire-and-forget frame pays no added latency
            self._send_remote([placeholder.buffer.pop()], placeholder)
            # fall through: open a one-turn window so the REST of a
            # burst coalesces
        if not placeholder.flush_scheduled:
            placeholder.flush_scheduled = True
            self.runtime.event.add_oneshot_handler(
                lambda: self._flush_remote(placeholder), 0.0)

    def _flush_remote(self, placeholder) -> None:
        placeholder.flush_scheduled = False
        if not placeholder.buffer:
            return
        entries, placeholder.buffer = placeholder.buffer, []
        self._send_remote(entries, placeholder)

    def _send_remote(self, entries, placeholder) -> None:
        if not placeholder.found:
            if self._recovery_enabled:
                # discovery raced away mid-buffer: hold the hops for the
                # next candidate (request hops stay lease-governed; a
                # stale request whose hop already retired is dropped)
                for entry, one_way in entries:
                    if one_way or entry[3] in self._pending_remote:
                        self._buffer_entry(placeholder, entry, one_way)
                return
            # legacy fail-fast: fail the hops cleanly (never sent, so
            # outstanding was never incremented)
            for entry, one_way in entries:
                if not one_way:
                    pending = self._pending_remote.pop(entry[3], None)
                    if pending is not None:
                        pending.cancel(self.runtime.event)
                        self._retire_hop(entry[3])
                        self.resume_frame(
                            pending.frame, pending.node_name, RuntimeError(
                                f"remote element {pending.node_name} left "
                                f"before send"))
            return
        one_way = [entry for entry, ow in entries if ow]
        # a request whose hop already settled (reply raced the resend,
        # stream destroyed) must not ship again
        request = [entry for entry, ow in entries
                   if not ow and entry[3] in self._pending_remote]
        if one_way:
            self._wire_counters["request_envelopes"].inc()
            self._wire_counters["request_frames"].inc(len(one_way))
            if len(one_way) == 1:
                placeholder.proxy.process_frame(*one_way[0])
            else:
                placeholder.proxy.process_frames(one_way)
        if request:
            sent_at = time.perf_counter()
            for entry in request:
                hop = self._pending_remote[entry[3]]
                hop.sent = True
                hop.sent_to = placeholder.topic_path
                hop.attempt_started = sent_at
            placeholder.outstanding += len(request)
            self._wire_counters["request_envelopes"].inc()
            self._wire_counters["request_frames"].inc(len(request))
            # a tenant-tagged solo entry must ship in the COALESCED
            # form: as the last positional of a bare RPC its tag is
            # indistinguishable from a header-level tenant marker and
            # the receiving actor's pop_tenant would strip it (a trace
            # in that slot survives — the actor re-injects it as the
            # ambient context, but there is no ambient tenant)
            if len(request) == 1 and \
                    not wire.is_tenant_fields(request[0][-1]):
                placeholder.proxy.process_frame_remote(*request[0])
            else:
                placeholder.proxy.process_frames_remote(request)

    def _hop_settled(self, node_name) -> None:
        """A reply (or expiry) retired one hop: the link has capacity —
        flush anything the coalescer buffered meanwhile."""
        placeholder = self._remote.get(node_name)
        if placeholder is None:
            return
        placeholder.outstanding = max(0, placeholder.outstanding - 1)
        if placeholder.buffer:
            self._flush_remote(placeholder)

    def _remote_hop_expired(self, hop_id) -> None:
        hop_id = str(hop_id)
        pending = self._pending_remote.get(hop_id)
        if pending is None:
            return
        pending.lease = None            # the oneshot just fired
        if pending.sent:
            pending.sent = False
            self._hop_settled(pending.node_name)
        self._record_attempt_span(pending, hop_id, "timeout")
        budget = None
        if pending.trace is not None:
            budget = pending.trace.remaining(
                self.runtime.event.clock.now())
        if pending.attempts < self.remote_retries:
            # bounded retry: exponential backoff + seeded jitter, and
            # rotate to another discovered candidate first — a timeout
            # against a wedged service recovers via its peer
            delay = jittered_backoff(
                self.remote_backoff, pending.attempts + 1,
                self.remote_backoff_max, self.retry_jitter,
                self._retry_rng)
            if budget is not None and budget <= delay:
                # deadline propagation (ISSUE 5): the backoff would
                # land past the frame's end-to-end SLO — never schedule
                # a retry past the budget; fail fast instead, charged
                # to the stream failure budget below
                self._fail_hop_deadline(pending, hop_id, budget, delay)
                return
            pending.attempts += 1
            self.recovery_stats["retries"] += 1
            placeholder = self._remote.get(pending.node_name)
            if placeholder is None or pending.sent_to is None \
                    or pending.sent_to == placeholder.topic_path:
                # rotate only while the active candidate is still the
                # one that timed this hop out: a burst of simultaneous
                # expiries must advance ONCE, not once per expired hop
                # (an even burst would land back on the dead candidate)
                self._rotate_candidate(pending.node_name)
            pending.resend_timer = self.runtime.event.add_oneshot_handler(
                lambda: self._resend_hop(hop_id), delay)
            return
        if budget is not None and budget <= 0:
            self._fail_hop_deadline(pending, hop_id, budget, 0.0)
            return
        self._pending_remote.pop(hop_id, None)
        self._retire_hop(hop_id)
        self._purge_buffered_hop(pending.node_name, hop_id)
        self._record_hop_span(pending, hop_id, "timeout")
        detail = f" after {pending.attempts} retries" \
            if pending.attempts else ""
        self.resume_frame(pending.frame, pending.node_name, TimeoutError(
            f"remote element {pending.node_name}: no reply within "
            f"{self.remote_timeout}s{detail}"))

    def _fail_hop_deadline(self, pending: _PendingHop, hop_id: str,
                           budget: float, delay: float) -> None:
        """Retire a hop whose end-to-end deadline budget is exhausted:
        fail the frame fast with a diagnostic instead of retrying past
        the SLO.  The failure flows through resume_frame → _fail_frame,
        so it is charged to the stream failure budget."""
        self._pending_remote.pop(hop_id, None)
        self.recovery_stats["deadline_exceeded"] += 1
        self._retire_hop(hop_id)
        self._purge_buffered_hop(pending.node_name, hop_id)
        self._record_hop_span(pending, hop_id, "deadline")
        if delay > 0:
            detail = (f"remaining budget {max(budget, 0.0):.3f}s < "
                      f"next backoff {delay:.3f}s")
        else:
            detail = f"remaining budget {max(budget, 0.0):.3f}s"
        self.resume_frame(pending.frame, pending.node_name, TimeoutError(
            f"remote element {pending.node_name}: deadline exhausted "
            f"after {pending.attempts} retries ({detail})"))

    # -- hop span recording (tracer-gated, ISSUE 5) -------------------------
    def _record_attempt_span(self, pending: _PendingHop, hop_id: str,
                             outcome: str) -> None:
        """One wire attempt settled (reply, or timeout before retry)."""
        trc = tracing.tracer
        if not trc.enabled or pending.trace is None \
                or not pending.attempt_started:
            return
        now = time.perf_counter()
        trc.record(f"hop_attempt:{pending.node_name}",
                   pending.attempt_started, now - pending.attempt_started,
                   context=pending.trace, cat="hop", proc=self.name,
                   span_id=tracing.new_span_id(),
                   args={"hop_id": hop_id, "attempt": pending.attempts,
                         "outcome": outcome,
                         "sent_to": pending.sent_to or ""})
        pending.attempt_started = 0.0

    def _record_hop_span(self, pending: _PendingHop, hop_id: str,
                         outcome: str) -> None:
        """The whole request/response hop settled (every exit path)."""
        duration = time.perf_counter() - pending.hop_started \
            if pending.hop_started else 0.0
        self._hop_seconds.observe(duration)
        trc = tracing.tracer
        if not trc.enabled or pending.trace is None:
            return
        trc.record(f"hop:{pending.node_name}", pending.hop_started,
                   duration, context=pending.trace, cat="hop",
                   proc=self.name,
                   args={"hop_id": hop_id, "attempts": pending.attempts,
                         "outcome": outcome})

    def _rotate_candidate(self, node_name: str) -> None:
        """Advance a remote node to its next discovered candidate (no-op
        with fewer than two).  Role-aware (ISSUE 14): when the active
        candidate advertises a role tag and SAME-role alternatives
        exist, rotation stays within them — a filter loose enough to
        match a mixed prefill/decode fleet must not fail a decode hop
        over onto a prefill runtime."""
        placeholder = self._remote.get(node_name)
        if placeholder is None or len(placeholder.candidates) < 2:
            return
        order = list(placeholder.candidates)
        role = placeholder.roles.get(placeholder.topic_path, "")
        same_role = [t for t in order
                     if placeholder.roles.get(t, "") == role]
        if placeholder.topic_path in same_role and len(same_role) > 1:
            order = same_role
        try:
            index = order.index(placeholder.topic_path)
        except ValueError:
            index = -1
        next_topic = order[(index + 1) % len(order)]
        if next_topic != placeholder.topic_path:
            self._activate_remote(node_name, next_topic, failover=True)

    def _resend_hop(self, hop_id: str) -> None:
        """Re-ship a pending hop (retry after timeout, or redirect after
        failover) under a fresh timeout lease, with the SAME hop id so
        duplicate replies dedup instead of double-resuming the frame."""
        hop_id = str(hop_id)
        pending = self._pending_remote.get(hop_id)
        if pending is None:
            return
        pending.resend_timer = None
        if pending.frame.stream.state == "stop":
            self._pending_remote.pop(hop_id, None)
            pending.cancel(self.runtime.event)
            self._retire_hop(hop_id)
            self._purge_buffered_hop(pending.node_name, hop_id)
            return
        placeholder = self._remote.get(pending.node_name)
        if placeholder is None:
            return
        self._arm_hop_lease(pending, hop_id)
        # drop any still-buffered copy of this hop before re-queueing
        self._purge_buffered_hop(pending.node_name, hop_id)
        entry = self._hop_entry(pending, hop_id)
        if pending.sent:
            # the in-flight copy is being superseded; release its slot
            pending.sent = False
            placeholder.outstanding = max(0, placeholder.outstanding - 1)
        if placeholder.found:
            self._send_remote([(entry, False)], placeholder)
        else:
            self._buffer_entry(placeholder, entry, one_way=False)

    def _retire_hop(self, hop_id: str) -> None:
        """Remember a settled hop id so a late duplicate reply is
        recognized as such (bounded ring)."""
        self._retired_hops[str(hop_id)] = True
        while len(self._retired_hops) > _RETIRED_HOP_CAP:
            self._retired_hops.pop(next(iter(self._retired_hops)))

    def resume_remote_frame(self, hop_id, ok, outputs=None, elided=None):
        """Reply entry (invoked over the wire by the serving pipeline).
        `elided` names identity-passthrough outputs the serving side
        did not echo: they are restored from the inputs this hop sent —
        only those, so a genuinely dropped output still fails loudly.

        Duplicate replies (retried requests, failover redirects, chaos
        duplication) dedup here: the first reply pops the pending hop,
        later ones find it retired and are counted, not warned."""
        hop_id = str(hop_id)
        pending = self._pending_remote.pop(hop_id, None)
        if pending is None:
            if hop_id in self._retired_hops:
                self.recovery_stats["dup_replies"] += 1
                self.logger.debug("pipeline %s: duplicate reply for "
                                  "settled hop %s", self.name, hop_id)
            else:
                self.logger.warning("pipeline %s: stale remote reply %r",
                                    self.name, hop_id)
            return
        frame, node_name = pending.frame, pending.node_name
        was_sent = pending.sent
        pending.cancel(self.runtime.event)
        self._purge_buffered_hop(node_name, hop_id)
        self._retire_hop(hop_id)
        if was_sent:
            self._hop_settled(node_name)
        replied_ok = str(ok) in ("true", "True")
        outcome = "ok" if replied_ok else "failed"
        self._record_attempt_span(pending, hop_id, outcome)
        self._record_hop_span(pending, hop_id, outcome)
        if not replied_ok:
            self.resume_frame(frame, node_name, RuntimeError(
                f"remote element {node_name} failed: {outputs!r}"))
            return
        outputs = dict(outputs or {})
        sent_inputs = pending.inputs or {}
        for key in elided or []:
            if key in sent_inputs:
                outputs.setdefault(key, sent_inputs[key])
        self.resume_frame(frame, node_name, outputs)

    def resume_remote_frames(self, entries):
        """Coalesced reply entry: one envelope, many hop replies."""
        for entry in entries or []:
            if isinstance(entry, (list, tuple)) and len(entry) >= 2:
                self.resume_remote_frame(*entry[:4])

    def process_frame_remote(self, stream_id, inputs, reply_topic, hop_id,
                             trace=None, tenant=None):
        """Serving entry: walk a frame for a remote caller and reply with
        the final swag when it completes (including through DEFERRED
        elements).

        At-least-once callers (retries, chaos duplication) may deliver
        the same hop twice: the first request walks, a duplicate while
        the walk is still running is skipped (its reply goes out when
        the walk completes), and a duplicate of a COMPLETED hop replays
        the cached reply — the original may have been lost on the wire.

        `trace` (optional trailing entry field) is the caller's hop
        trace context: the walk runs under it — its spans share the
        caller's trace id — and a request arriving with its deadline
        budget already spent is rejected fast instead of walked (the
        caller has, by definition, stopped waiting).

        `tenant` (optional trailing entry field, wire.tenant_fields) is
        the caller stream's tenant/tier tag.  With an admission gate
        configured (ISSUE 9) the request passes two further verdicts
        before any work: shed-early when the estimated queue wait
        cannot meet the remaining deadline budget (one cheap failure
        reply, and the caller fails over), then the per-tenant weighted
        fair queue.  Both markers are self-tagged, so a tenant tag
        arriving without a trace lands in the `trace` slot and is
        re-sorted here."""
        if tenant is None and wire.is_tenant_fields(trace):
            trace, tenant = None, trace
        tenant_name, tier = wire.parse_tenant(tenant)
        key = (str(reply_topic), str(hop_id))
        if key in self._served_hops:
            self.recovery_stats["dup_requests"] += 1
            cached = self._served_hops[key]
            if cached is not None:
                self._replay_reply(cached)
            return
        now = self.runtime.event.clock.now()
        context = tracing.TraceContext.from_fields(trace, now) \
            if trace is not None else tracing.current_trace()
        self._served_hops[key] = None       # walk in progress
        while len(self._served_hops) > _SERVED_HOP_CAP:
            # evict oldest COMPLETED entry: an in-progress (None) entry
            # dropped here would let a retry re-walk a side-effecting
            # frame and orphan the eventual reply caching
            stale = next((k for k, v in self._served_hops.items()
                          if v is not None), None)
            if stale is None:
                break
            evicted = self._served_hops.pop(stale)
            self._served_reply_bytes -= evicted[3]
            self._credit_tenant_reply_bytes(evicted[4], evicted[3])
        if context is not None and context.expired(now):
            # the failure reply is cached in the dedup ring, so a
            # duplicate of this dead request replays the verdict
            self.recovery_stats["deadline_rejected"] += 1
            if self.admission is not None:
                self.admission.count_rejected(tenant_name, tier,
                                              "expired")
            self._shim_failure_reply(
                key, stream_id,
                f"deadline exceeded before processing (hop {hop_id})")
            return
        if self.admission is not None:
            remaining = context.remaining(now) \
                if context is not None else None
            shed, wait = self.admission.shed_early(remaining)
            if shed:
                # reject at the cheapest point: the dedup-cached reply
                # costs one control message, and the caller's retry
                # machinery rotates to another candidate instead of
                # queueing doomed work here (charged to the caller's
                # stream failure budget like deadline_rejected)
                self.recovery_stats["shed_early"] += 1
                self.admission.count_rejected(tenant_name, tier,
                                              "shed-early")
                self._shim_failure_reply(
                    key, stream_id,
                    f"shed-early: estimated queue wait {wait:.3f}s "
                    f"cannot meet remaining budget {remaining:.3f}s "
                    f"(hop {hop_id})")
                return
            item = (key, str(stream_id), dict(inputs or {}), context,
                    tenant_name, tier)
            self._admitted_keys.add(key)
            self.admission.offer(tenant_name, item,
                                 shed=self._shed_admitted, tier=tier,
                                 dispatch=self._run_admitted)
            return
        self._serve_walk(key, str(stream_id), dict(inputs or {}),
                         context, tenant_name, tier)

    def _serve_walk(self, key, stream_id, inputs, context, tenant,
                    tier, verdict: str = "admitted",
                    queue_wait: float | None = None) -> None:
        """Run one admitted remote request's walk.  The tenant tag is
        stamped into the stream's parameters at creation, so elements
        and nested pipelines see it through get_parameter and further
        hops re-ship it (ISSUE 9).  The admission verdict and measured
        fair-queue wait are posted as a journey note under the frame's
        trace id BEFORE the walk runs — a ContinuousDecoder reached
        synchronously inside this walk claims them into its
        RequestJourney (ISSUE 12; engine-clock seconds, bounded
        handoff, no coupling between ops/ and serving/)."""
        if context is not None and context.trace_id:
            from .observe.journey import note_admission
            note_admission(context.trace_id, verdict,
                           queue_wait_s=queue_wait, tenant=tenant,
                           tier=tier)
        if tenant and self.auto_create_streams and \
                stream_id not in self.streams:
            self.create_stream(stream_id,
                               parameters={"tenant": tenant,
                                           "tier": tier})
        try:
            with tracing.activate(context):
                result = self.process_frame(stream_id, inputs,
                                            _reply_to=key,
                                            _reply_skip=inputs)
        except Exception as exc:
            self._shim_failure_reply(key, stream_id, repr(exc))
            raise
        if not result.ok:
            self._shim_failure_reply(key, stream_id, result.diagnostic)

    # -- admission gate plumbing (ISSUE 9) ----------------------------------
    def _run_admitted(self, item) -> None:
        key, stream_id, inputs, context, tenant, tier = item
        # the fair queue measured this frame's dwell as it drained it
        # (synchronously, just before this dispatch) — ONE measurement
        # feeds both the admission_queue_wait_seconds histogram and
        # the journey note
        queue_wait = self.admission.queue.last_dispatch_wait \
            if self.admission is not None else None
        self._serve_walk(key, stream_id, inputs, context, tenant, tier,
                         verdict="admitted", queue_wait=queue_wait)

    def _shed_admitted(self, item) -> None:
        """Fair-queue shed: the frame never ran — answer its caller so
        the dedup ring doesn't strand retries, and give back nothing
        (it never held an inflight credit)."""
        key, stream_id, _inputs, _context, tenant, _tier = item
        self._admitted_keys.discard(key)
        self.recovery_stats["admission_shed"] += 1
        self._shim_failure_reply(
            key, stream_id,
            f"shed: tenant {tenant or 'default'!r} over admission "
            f"budget")

    def _drain_admission(self) -> None:
        if self.admission is not None and self.admission.queue.depth():
            self.admission.drain(self._run_admitted)

    def _shim_failure_reply(self, key, stream_id, diagnostic) -> None:
        """Answer a remote request whose walk died before any frame
        could carry the reply address (unknown stream with auto-create
        off, start_stream raised): the reply is cached in the dedup
        ring, so the caller's retries replay this failure instead of
        being skipped as duplicates of a hop that will never complete."""
        if self._served_hops.get(key, True) is not None:
            return
        shim = Frame(stream=Stream(stream_id=str(stream_id),
                                   state="stop"),
                     frame_id=-1, reply_to=key)
        self._send_remote_reply(shim, False, {"diagnostic": diagnostic})

    def _cache_served_reply(self, key, kind, topic, data,
                            tenant: str = "") -> None:
        """Pin a completed reply for duplicate replay, under THREE
        bounds: the per-entry size cap, the caller tenant's sub-budget
        (_SERVED_REPLY_TENANT_BUDGET_BYTES — a tagged tenant over it
        demotes its OWN oldest replies first, so a flooder cannot evict
        the polite tenants' replay capacity; ISSUE 10), and the
        aggregate _SERVED_REPLY_BUDGET_BYTES pin.  Demotion is always
        to 'uncached' — still dedup-recognized as completed, just no
        longer replayable — 1024 entries of just-under-cap image
        replies must not pin a quarter gigabyte."""
        nbytes = _payload_nbytes(data)
        self._served_hops[key] = (kind, topic, data, nbytes, tenant)
        self._served_reply_bytes += nbytes
        if nbytes and tenant:
            self._served_reply_tenant_bytes[tenant] = \
                self._served_reply_tenant_bytes.get(tenant, 0) + nbytes
            while self._served_reply_tenant_bytes.get(tenant, 0) > \
                    _SERVED_REPLY_TENANT_BUDGET_BYTES:
                if not self._demote_oldest_reply(key, tenant=tenant):
                    break
        while self._served_reply_bytes > _SERVED_REPLY_BUDGET_BYTES:
            if not self._demote_oldest_reply(key):
                break

    def _demote_oldest_reply(self, keep_key, tenant: str | None = None) \
            -> bool:
        """Demote the oldest pinned reply (of `tenant`, or of anyone)
        to dedup-only; returns False when nothing is left to demote."""
        stale = next(
            (k for k, v in self._served_hops.items()
             if v is not None and v[3] and k != keep_key
             and (tenant is None or v[4] == tenant)), None)
        if stale is None:
            return False
        _, stale_topic, _, stale_nbytes, stale_tenant = \
            self._served_hops[stale]
        self._served_hops[stale] = \
            ("uncached", stale_topic, None, 0, stale_tenant)
        self._served_reply_bytes -= stale_nbytes
        self._credit_tenant_reply_bytes(stale_tenant, stale_nbytes)
        return True

    def _credit_tenant_reply_bytes(self, tenant: str, nbytes: int) -> None:
        if not tenant or not nbytes:
            return
        remaining = self._served_reply_tenant_bytes.get(tenant, 0) - nbytes
        if remaining > 0:
            self._served_reply_tenant_bytes[tenant] = remaining
        else:
            self._served_reply_tenant_bytes.pop(tenant, None)

    def _replay_reply(self, cached) -> None:
        """Re-send a cached reply for a duplicate of a completed hop."""
        kind, topic, data = cached[0], cached[1], cached[2]
        if kind == "uncached":
            self.logger.warning(
                "pipeline %s: duplicate of a completed hop whose reply "
                "was too large to cache; not replayed", self.name)
            return
        self.recovery_stats["replayed_replies"] += 1
        if kind == "bin":
            self._reply_buffer.setdefault(topic, []).append(data)
            if not self._reply_flush_scheduled:
                self._reply_flush_scheduled = True
                self.runtime.event.add_oneshot_handler(
                    self._flush_replies, 0.0)
        else:
            self.runtime.publish(topic, data)

    def process_frames(self, entries):
        """Coalesced one-way entry: one envelope, many (stream_id,
        inputs) frames — the per-message wire overhead amortizes across
        the burst (ISSUE 2 chunk coalescing)."""
        for entry in entries or []:
            if isinstance(entry, (list, tuple)) and len(entry) >= 2:
                self.process_frame(entry[0], dict(entry[1] or {}))

    def process_frames_remote(self, entries):
        """Coalesced request/response entry: one envelope, many
        (stream_id, inputs, reply_topic, hop_id[, trace][, tenant])
        frames — each frame's OWN trace context and tenant tag ride its
        entry, so coalescing never mixes trace ids, deadlines, or
        per-tenant budgets."""
        required = len(wire.HOP_ENTRY_FIELDS)
        limit = required + len(wire.HOP_ENTRY_OPTIONAL)
        for entry in entries or []:
            if isinstance(entry, (list, tuple)) and \
                    len(entry) >= required:
                self.process_frame_remote(*entry[:limit])

    def _fail_frame(self, frame, node_name, diagnostic) -> None:
        self.logger.error("pipeline %s stream %s frame %s: element %s "
                          "failed: %s", self.name, frame.stream_id,
                          frame.frame_id, node_name, diagnostic)
        self.recovery_stats["frames_failed"] += 1
        stream = frame.stream
        stream.last_diagnostic = f"{node_name}: {diagnostic}"
        if self.streams.get(stream.stream_id) is not stream:
            # nested as an element on the PARENT's stream: the parent
            # charges its own failure budget when our not-ok output
            # propagates — charging here too would double-count every
            # failure, and destroy_stream below could kill an unrelated
            # same-id stream this pipeline happens to own
            return
        stream.consecutive_failures += 1
        over_budget = \
            stream.consecutive_failures >= self.stream_failure_budget
        if frame.reply_to is not None:
            self._send_remote_reply(frame, False,
                                    {"diagnostic": str(diagnostic),
                                     "stream_stopped": over_budget})
        if not over_budget:
            # inside the per-stream failure budget: the frame is lost but
            # the stream survives — a transient remote fault must not
            # tear down a long-lived stream and leak its consumers
            return
        self.recovery_stats["streams_stopped"] += 1
        self.destroy_stream(frame.stream_id)

    def _send_remote_reply(self, frame, ok: bool, outputs: dict) -> None:
        import numpy as _np
        topic, hop_id = frame.reply_to
        # the caller stream's tenant tag (stamped into auto-created
        # stream parameters by _serve_walk) keys the reply replay
        # cache's per-tenant sub-budget
        tenant = str(frame.stream.parameters.get("tenant", "") or "")
        trc = tracing.tracer
        if trc.enabled and frame.trace is not None:
            # the serving-side "process" span: walk start → reply out
            # (DEFERRED parking included), child of the caller's hop
            now = time.perf_counter()
            started = frame.metrics.get("time_pipeline_start", now)
            trc.record("process", started, now - started,
                       context=frame.trace, cat="serving",
                       proc=self.name, span_id=tracing.new_span_id(),
                       args={"hop_id": str(hop_id), "ok": bool(ok),
                             "stream": frame.stream_id})
        elided: list = []
        if frame.reply_skip:
            # don't echo untouched binary inputs back over the wire
            # (the whole audio/image payload would ride every reply).
            # Elide ONLY read-only payload types (ndarray/bytes — wire
            # decode hands out read-only views, so the element cannot
            # have mutated them in place); the elided key list crosses
            # in the reply so the caller restores EXACTLY these from
            # its sent inputs and nothing else fails silently.
            elided = [k for k, v in outputs.items()
                      if frame.reply_skip.get(k) is v
                      and isinstance(v, (_np.ndarray, bytes))]
            outputs = {k: v for k, v in outputs.items()
                       if k not in elided}
        key = (topic, str(hop_id))
        if self.admission is not None and key in self._admitted_keys:
            # the admitted frame's reply is going out: return its
            # inflight credit and release the next queued frame on a
            # fresh engine turn (never recurse inside a drain)
            self._admitted_keys.discard(key)
            self.admission.release()
            self.runtime.event.add_oneshot_handler(
                self._drain_admission, 0.0)
        if wire.supports_binary(self.runtime.message):
            # binary envelope reply: tensors cross back out-of-band
            # (zero text round-trip); replies to one caller coalesce
            # per engine turn
            payload = {k: v for k, v in outputs.items()
                       if isinstance(v, (str, int, float, bool, bytes,
                                         list, tuple, dict))
                       or wire.contains_binary(v)}
            entry = [hop_id, bool(ok), payload, elided]
            if key in self._served_hops:
                if _payload_nbytes(payload) <= _SERVED_REPLY_CACHE_BYTES:
                    self._cache_served_reply(key, "bin", topic, entry,
                                             tenant=tenant)
                else:
                    # completed, but too heavy to pin for replay: a
                    # duplicate request is still recognized (never
                    # re-walked), it just can't be answered again
                    self._served_hops[key] = \
                        ("uncached", topic, None, 0, tenant)
            self._reply_buffer.setdefault(topic, []).append(entry)
            if not self._reply_flush_scheduled:
                self._reply_flush_scheduled = True
                self.runtime.event.add_oneshot_handler(
                    self._flush_replies, 0.0)
            return
        from .utils import generate
        # text fallback: only wire-expressible values cross back —
        # tensors must be PE_DataEncode'd (to str) by the serving graph
        safe = {k: v for k, v in outputs.items()
                if isinstance(v, (str, int, float, bool))}
        text = generate("resume_remote_frame", [hop_id, ok, safe, elided])
        if key in self._served_hops:
            self._cache_served_reply(key, "text", topic, text,
                                     tenant=tenant)
        self.runtime.publish(topic, text)

    def _flush_replies(self) -> None:
        self._reply_flush_scheduled = False
        buffered, self._reply_buffer = self._reply_buffer, {}
        for topic, entries in buffered.items():
            if len(entries) == 1:
                payload = wire.encode_envelope("resume_remote_frame",
                                               entries[0])
            else:
                payload = wire.encode_envelope("resume_remote_frames",
                                               [entries])
            self._wire_counters["reply_envelopes"].inc()
            self._wire_counters["reply_frames"].inc(len(entries))
            self.runtime.publish(topic, payload)

    def stop(self) -> None:
        if self._admission_timer is not None:
            self.runtime.event.remove_timer_handler(self._admission_timer)
            self._admission_timer = None
        if self.admission is not None:
            # queued-but-never-run frames still owe their callers a
            # reply — shed them through the normal failure path first
            self.admission.queue.shed_all(reason="shutdown")
        for stream_id in list(self.streams):
            self.destroy_stream(stream_id)
        # any hop that survived stream teardown (e.g. nested frames on
        # foreign streams) still holds timers: cancel them all
        for hop_id, pending in list(self._pending_remote.items()):
            pending.cancel(self.runtime.event)
            self._retire_hop(hop_id)
        self._pending_remote.clear()
        for node in self.graph.nodes():
            element = node.element
            if isinstance(element, PipelineElement) and element is not self:
                element.stop()
        super().stop()
