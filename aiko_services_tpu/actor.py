# Actor layer: message → method-call RPC over per-actor mailboxes.
#
# Capability parity with the reference actor layer
# (reference: aiko_services/actor.py:105-295 and
# transport/transport_mqtt.py:34-127):
#   * ActorMessage — deferred method invocation (target, command, args);
#   * Actor — a Service with `control` and `in` mailboxes (control drains
#     first), inbound payloads parsed as S-expressions and dispatched as
#     method calls; built-in EC share with lifecycle / log_level;
#   * get_remote_proxy — reflects a protocol class's public methods into a
#     proxy whose calls serialize to S-expressions published to the target's
#     `in` topic (the "function call → message" half of the RPC);
#   * ActorDiscovery — handler registration over the ServicesCache.

from __future__ import annotations

import inspect
import time

from .observe import tracing
from .service import Service, ServiceFilter, ServiceProtocol
from .share import ECProducer, ServicesCache
from .transport import wire
from .utils import get_logger, parse

__all__ = ["ActorMessage", "Actor", "get_remote_proxy", "get_public_methods",
           "ActorDiscovery", "PROTOCOL_ACTOR"]

PROTOCOL_ACTOR = ServiceProtocol("actor")


class ActorMessage:
    __slots__ = ("target", "command", "arguments", "trace")

    def __init__(self, target, command: str, arguments, trace=None):
        self.target = target
        self.command = command
        self.arguments = arguments
        # trace context the message arrived under (envelope header /
        # sexpr marker): activated for the duration of the call, so
        # the handler — and anything it spawns — inherits the caller's
        # trace id and deadline
        self.trace = trace

    def invoke(self, logger=None) -> None:
        method = getattr(self.target, self.command, None)
        if method is None or self.command.startswith("_") \
                or not callable(method):
            if logger:
                logger.warning("actor %s: no method %r",
                               getattr(self.target, "name", "?"),
                               self.command)
            return
        try:
            with tracing.activate(self.trace):
                method(*self.arguments)
        except Exception:
            if logger:
                logger.exception("actor %s: %s%r raised",
                                 getattr(self.target, "name", "?"),
                                 self.command, tuple(self.arguments))


class Actor(Service):
    def __init__(self, runtime, name: str, protocol=None, tags=None,
                 share: dict | None = None):
        super().__init__(runtime, name, protocol or PROTOCOL_ACTOR, tags)
        self.logger = get_logger(f"actor.{name}")
        # distributed logging (runtime-gated): this actor's records also
        # publish to {topic_path}/log, where the Recorder's namespace
        # filter and the dashboard log page pick them up
        self._transport_log_handler = None
        if getattr(runtime, "log_transport", False):
            import logging as _logging
            from .utils.logger import TransportLoggingHandler
            handler = TransportLoggingHandler(lambda: runtime.message,
                                              self.topic_log)
            handler.setFormatter(_logging.Formatter(
                "%(levelname)s %(name)s: %(message)s"))
            self.logger.addHandler(handler)
            self._transport_log_handler = handler
        base_share = {
            "lifecycle": "ready",
            "log_level": "INFO",
            "running": True,
        }
        base_share.update(share or {})
        self.ec_producer = ECProducer(self, base_share)
        self.ec_producer.add_handler(self._share_changed)
        self.share = self.ec_producer.share

        self._mailbox_control = f"{self.topic_path}/control#mb"
        self._mailbox_in = f"{self.topic_path}/in#mb"
        # control registered first → drains with priority
        runtime.event.add_mailbox_handler(self._mailbox_handler,
                                          self._mailbox_control)
        runtime.event.add_mailbox_handler(self._mailbox_handler,
                                          self._mailbox_in)
        runtime.add_message_handler(self._topic_in_handler, self.topic_in)

    # -- inbound -----------------------------------------------------------
    def _topic_in_handler(self, _topic, payload) -> None:
        started = time.perf_counter()
        try:
            if wire.is_envelope(payload):
                # binary wire envelope: tensors arrive as zero-copy
                # views, scalars keep sexpr (string) semantics
                command, params, trace_fields = \
                    wire.decode_envelope(payload, with_trace=True)
            else:
                command, params = parse(payload)
                wire.pop_tenant(params)     # appended after trace
                trace_fields = wire.pop_trace(params)
        except Exception:
            self.logger.warning("%s: unparseable payload %r",
                                self.name, payload)
            return
        context = None
        if trace_fields is not None:
            now = self.runtime.event.clock.now()
            context = tracing.TraceContext.from_fields(trace_fields, now)
            trc = tracing.tracer
            if trc.enabled and context is not None:
                decode_dur = time.perf_counter() - started
                if context.sent is not None:
                    # wire transit (engine-clock seconds — virtual in
                    # deterministic runs, deliberately: injected chaos
                    # delays show up here), recordable only when sender
                    # and receiver clocks are comparable; the span ENDS
                    # at arrival, so decode/queue/process follow it
                    transit = now - context.sent
                    if 0.0 <= transit <= tracing.CLOCK_COMPARABLE_HORIZON:
                        trc.record("deliver", started - transit, transit,
                                   context=context, cat="wire",
                                   proc=self.name,
                                   span_id=tracing.new_span_id(),
                                   args={"command": command})
                trc.record("decode", started, decode_dur,
                           context=context, cat="wire", proc=self.name,
                           span_id=tracing.new_span_id(),
                           args={"command": command})
        if command:
            self._post_message(command, params, trace=context)

    def _post_message(self, command: str, arguments, trace=None) -> None:
        mailbox = self._mailbox_control if command.startswith("control_") \
            else self._mailbox_in
        self.runtime.event.mailbox_put(
            mailbox, ActorMessage(self, command, arguments, trace=trace))

    def _mailbox_handler(self, _name, message, put_time) -> None:
        trc = tracing.tracer
        if trc.enabled and message.trace is not None:
            # mailbox dwell: engine-clock put → drain (the "queue" hop).
            # Duration is engine-clock seconds — virtual in
            # deterministic runs, on purpose: the dwell the scheduler
            # imposed is the signal, not the wall time of the drain.
            # The span ENDS at the drain, like deliver ends at arrival.
            waited = max(0.0, self.runtime.event.clock.now() - put_time)
            now = time.perf_counter()
            trc.record("queue", now - waited, waited,
                       context=message.trace, cat="wire", proc=self.name,
                       span_id=tracing.new_span_id(),
                       args={"command": message.command})
        message.invoke(self.logger)

    # -- local deferred invocation (used by pipelines, tests) --------------
    def post(self, command: str, *arguments) -> None:
        self._post_message(command, list(arguments))

    # -- share change plumbing ---------------------------------------------
    def _share_changed(self, command, name, value) -> None:
        if name == "log_level" and command in ("add", "update"):
            try:
                self.logger.setLevel(str(value))
            except ValueError:
                pass

    # -- built-in control methods ------------------------------------------
    def control_stop(self) -> None:
        self.ec_producer.update("lifecycle", "stopped")
        self.stop()

    def control_drain(self, drain_s="0") -> None:
        """Graceful wind-down request (ISSUE 19): the lifecycle
        manager's planned retirements publish `(control_drain N)`
        instead of `(control_stop)`.  The base actor has nothing to
        drain, so the default degrades to an immediate stop; serving
        actors override this to drain their decoder, migrate session
        KV, and stop themselves when (or before) the deadline the
        manager holds as the crash-path fallback."""
        del drain_s
        self.control_stop()

    def stop(self) -> None:
        if self._transport_log_handler is not None:
            # loggers are global by name — leaked handlers would double-
            # publish for a later same-named actor
            self.logger.removeHandler(self._transport_log_handler)
            self._transport_log_handler = None
        self.runtime.event.remove_mailbox_handler(self._mailbox_control)
        self.runtime.event.remove_mailbox_handler(self._mailbox_in)
        self.runtime.remove_message_handler(self._topic_in_handler,
                                            self.topic_in)
        self.ec_producer.terminate()
        super().stop()


def get_public_methods(protocol_class) -> list[str]:
    """Public callables declared by a protocol class (not inherited from
    object, not underscore-prefixed)."""
    methods = []
    for name, member in inspect.getmembers(protocol_class):
        if name.startswith("_") or not callable(member):
            continue
        if getattr(object, name, None) is member:
            continue
        methods.append(name)
    return methods


class _RemoteProxy:
    def __init__(self, runtime, topic_in):
        self._runtime = runtime
        self._topic_in = topic_in

    def __repr__(self):
        return f"RemoteProxy({self._topic_in})"


def get_remote_proxy(runtime, topic_in: str, protocol_class,
                     codec_hints=None):
    """Build a proxy object: calling proxy.method(a, b) publishes
    "(method a b)" to `topic_in` (fire-and-forget, like the reference).

    When the runtime's transport is binary-capable and an argument holds
    ndarray/bytes values, the call ships as a binary wire envelope
    instead of text — tensors cross without a text round-trip.
    codec_hints ({dict_key: codec}) opts named arrays into a lossy wire
    codec (see transport/wire.py).

    An ambient trace context (observe/tracing.py) at call time rides
    the wire — envelope header on binary transports, trailing sexpr
    marker on text — so the receiving actor's dispatch inherits the
    caller's trace id and remaining deadline."""
    proxy = _RemoteProxy(runtime, topic_in)
    for method_name in get_public_methods(protocol_class):
        def remote_call(*args, _name=method_name, **kwargs):
            if kwargs:
                raise TypeError("remote calls are positional-only")
            context = tracing.current_trace()
            trace_fields = None
            if context is not None:
                trace_fields = context.to_fields(
                    runtime.event.clock.now())
            started = time.perf_counter()
            payload = wire.encode_rpc(
                _name, list(args), transport=runtime.message,
                codec_hints=codec_hints, trace=trace_fields)
            trc = tracing.tracer
            if trc.enabled and context is not None:
                trc.record("encode", started,
                           time.perf_counter() - started,
                           context=context, cat="wire",
                           proc=getattr(runtime, "name", ""),
                           span_id=tracing.new_span_id(),
                           args={"command": _name})
            runtime.publish(topic_in, payload)
        setattr(proxy, method_name, remote_call)
    return proxy


class ActorDiscovery:
    """Find actors by ServiceFilter and get live add/remove callbacks."""

    def __init__(self, runtime, services_cache: ServicesCache | None = None):
        self.runtime = runtime
        self.cache = services_cache or ServicesCache(runtime)

    def add_handler(self, handler, service_filter: ServiceFilter) -> None:
        self.cache.add_handler(handler, service_filter)

    def remove_handler(self, handler) -> None:
        self.cache.remove_handler(handler)

    def share_services(self) -> list:
        return list(self.cache.services)
