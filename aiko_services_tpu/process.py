# Process runtime: identity, transport, topic routing, registrar bootstrap.
#
# Capability parity with the reference process runtime
# (reference: aiko_services/process.py:76-330): topic roots
# {namespace}/{hostname}/{process_id}, wildcard topic→handler routing,
# service table with incrementing service ids, last-will liveness on the
# process state topic, and the registrar bootstrap protocol
# "(primary found ...)" / "(primary absent)".
#
# Design changes:
#   * instantiable ProcessRuntime — many logical "processes" can share one
#     EventEngine + MemoryBroker, so whole multi-node systems run
#     deterministically inside a single pytest (the reference needs a live
#     mosquitto and real OS processes);
#   * transport injected via factory (memory default, MQTT optional);
#   * inbound messages always marshalled from the transport thread onto the
#     event engine before any handler runs.

from __future__ import annotations

import itertools
import os

from .connection import Connection, ConnectionState
from .event import EventEngine
from .transport.memory import MemoryMessage
from .transport.message import topic_matches
from .transport.wire import is_envelope as wire_is_envelope
from .utils import (
    generate, get_hostname, get_namespace, get_username, get_logger, parse,
)

__all__ = ["ProcessRuntime", "REGISTRAR_BOOT_SUFFIX", "STATE_ABSENT"]

REGISTRAR_BOOT_SUFFIX = "service/registrar"
STATE_ABSENT = "(absent)"
_process_counter = itertools.count()


class ProcessRuntime:
    """One logical process on the control plane."""

    def __init__(self, name: str | None = None, engine: EventEngine = None,
                 transport_factory=None, namespace: str | None = None,
                 process_id: str | None = None,
                 terminate_on_registrar_absent: bool = False,
                 log_transport: bool | None = None):
        self.namespace = namespace or get_namespace()
        self.hostname = get_hostname()
        # unique id even when many runtimes share one OS process (tests)
        self.process_id = process_id or \
            f"{os.getpid()}-{next(_process_counter)}"
        self.username = get_username()
        self.topic_path = \
            f"{self.namespace}/{self.hostname}/{self.process_id}"
        self.topic_state = f"{self.topic_path}/0/state"
        self.topic_registrar_boot = \
            f"{self.namespace}/{REGISTRAR_BOOT_SUFFIX}"
        self.name = name or self.process_id
        self.logger = get_logger(f"process.{self.name}")
        # distributed logging: actors publish their records to
        # {topic_path}/{sid}/log (reference gate: AIKO_LOG_MQTT,
        # process.py:103-113 there)
        self.log_transport = log_transport if log_transport is not None \
            else os.environ.get("AIKO_TPU_LOG_TRANSPORT", "0") == "1"

        self.event = engine or EventEngine()
        self.connection = Connection()
        self.registrar: dict | None = None     # {"topic_path": ..., ...}
        self.terminate_on_registrar_absent = terminate_on_registrar_absent

        self._transport_factory = transport_factory or self._default_factory
        self.message = None
        self.peer = None        # PeerHost once enable_peer() is called
        self._message_handlers: list[tuple[str, object]] = []
        self._exact_handlers: dict[str, list] = {}
        self._wildcard_handlers: list[tuple[str, object]] = []
        self._binary_topics: set[str] = set()
        self._services: dict[int, object] = {}
        self._service_counter = itertools.count(1)
        self._registrar_handlers = []
        self._queue_name = f"message:{self.topic_path}"
        self._initialized = False

    @property
    def transport_name(self) -> str:
        return "memory" if isinstance(self.message, MemoryMessage) else "mqtt"

    @staticmethod
    def _default_factory(on_message, lwt_topic, lwt_payload, lwt_retain):
        return MemoryMessage(on_message=on_message, lwt_topic=lwt_topic,
                             lwt_payload=lwt_payload, lwt_retain=lwt_retain)

    # -- lifecycle ---------------------------------------------------------
    def initialize(self) -> "ProcessRuntime":
        if self._initialized:
            return self
        self._initialized = True
        self.event.add_queue_handler(self._on_message_queue, self._queue_name)
        self.add_message_handler(self._on_registrar,
                                 self.topic_registrar_boot)
        self.message = self._transport_factory(
            self._on_transport_message,
            self.topic_state, STATE_ABSENT, True)
        for topic, _ in self._message_handlers:
            self.message.subscribe(topic)
        for topic in self._binary_topics:
            self._mark_data_plane(topic)
        self.message.connect()
        self.connection.update(ConnectionState.TRANSPORT)
        # liveness: retained presence marker cleared by our LWT on death
        self.message.publish(self.topic_state, "(present)", retain=True)
        return self

    def run(self, loop_when_no_handlers=False) -> None:
        self.initialize()
        self.event.loop(loop_when_no_handlers)

    def terminate(self, graceful: bool = True) -> None:
        # stop() overrides run teardown (e.g. a primary registrar clears its
        # retained boot record and announces "(primary absent)")
        if self.peer is not None:
            self.peer.close()
            self.peer = None
        for service_id, service in list(self._services.items()):
            stop = getattr(service, "stop", None)
            if stop:
                stop()
            else:
                self.remove_service(service_id)
        if self.message is not None:
            if graceful:
                # explicit absent marker (broker LWT only fires on crash)
                self.message.publish(self.topic_state, STATE_ABSENT,
                                     retain=True)
                self.message.disconnect()
            else:
                crash = getattr(self.message, "crash", None)
                crash() if crash else self.message.disconnect()
        self.event.remove_queue_handler(self._queue_name)
        self.connection.update(ConnectionState.NONE)

    # -- inbound message path ---------------------------------------------
    def _on_transport_message(self, topic: str, payload,
                              ack=None) -> None:
        # may be called on a transport thread: marshal onto the event
        # engine.  `ack` (optional) is invoked when the item is drained
        # — the peer data plane uses it to bound its in-flight window
        self.event.queue_put(self._queue_name,
                             (topic, payload) if ack is None
                             else (topic, payload, ack))

    def _on_message_queue(self, _name, item, _put_time) -> None:
        topic, payload = item[0], item[1]
        if len(item) > 2:
            item[2]()           # delivery ack: the queue slot is free
        if isinstance(payload, bytes) and \
                not self._is_binary_topic(topic) and \
                not wire_is_envelope(payload):
            try:
                payload = payload.decode("utf-8")
            except UnicodeDecodeError:
                pass
        # exact handlers hash-match; only wildcard patterns scan — a
        # linear topic_matches walk here is O(handlers) per message,
        # which turns an N-consumer fan-out into O(N²) (the reference's
        # documented bottleneck, its lifecycle.py:18-24)
        for handler in list(self._exact_handlers.get(topic, ())):
            handler(topic, payload)
        for pattern, handler in list(self._wildcard_handlers):
            if topic_matches(pattern, topic):
                handler(topic, payload)

    def _is_binary_topic(self, topic: str) -> bool:
        return any(topic_matches(p, topic) for p in self._binary_topics)

    def _mark_data_plane(self, topic: str) -> None:
        """Binary topics carry tensor/media streams: give them the
        transport's data-plane treatment (bounded per-client queues
        with a drop policy on the memory broker) so a slow consumer
        sheds stale frames instead of growing without bound."""
        mark = getattr(self.message, "mark_data_plane", None)
        if mark is not None:
            mark(topic)

    def add_message_handler(self, handler, topic: str,
                            binary: bool = False) -> None:
        self._message_handlers.append((topic, handler))
        if "+" in topic or "#" in topic:
            self._wildcard_handlers.append((topic, handler))
        else:
            self._exact_handlers.setdefault(topic, []).append(handler)
        if binary:
            self._binary_topics.add(topic)
            if self.message is not None:
                self._mark_data_plane(topic)
        if self.message is not None:
            self.message.subscribe(topic)

    def remove_message_handler(self, handler, topic: str) -> None:
        self._message_handlers = [
            (t, h) for t, h in self._message_handlers
            if not (t == topic and h == handler)]
        self._wildcard_handlers = [
            (t, h) for t, h in self._wildcard_handlers
            if not (t == topic and h == handler)]
        exact = self._exact_handlers.get(topic)
        if exact is not None:
            self._exact_handlers[topic] = [h for h in exact
                                           if h != handler]
            if not self._exact_handlers[topic]:
                del self._exact_handlers[topic]
        if self.message is not None and \
                not any(t == topic for t, _ in self._message_handlers):
            self.message.unsubscribe(topic)

    def publish(self, topic: str, payload, retain: bool = False,
                wait: bool = False) -> None:
        # peer data plane (ISSUE 6): binary envelopes bound for a topic
        # with a live negotiated channel bypass the broker entirely;
        # everything else — control text, retained state, unpinned
        # topics, dead channels — falls through to the broker path
        if self.peer is not None and not retain and \
                self.peer.maybe_send(topic, payload):
            return
        self.message.publish(topic, payload, retain, wait)

    # -- peer data plane (ISSUE 6) ----------------------------------------
    def enable_peer(self, kinds=("mem",), **kwargs):
        """Turn on the peer data plane for this runtime: services
        registered by this process advertise a direct-channel endpoint
        (tag "peer=..."), inbound handshakes are answered, and
        publish() pins negotiated data-plane traffic off the broker.
        Idempotent; returns the PeerHost."""
        if self.peer is None:
            from .transport.peer import PeerHost
            self.peer = PeerHost(self, kinds=kinds, **kwargs)
            # services registered before enabling re-advertise with the
            # endpoint tag so existing discovery records pick it up
            for service in self._services.values():
                service.add_tags([self.peer.tag])
                if self.registrar is not None and self.message is not None:
                    self._register_service(service)
        return self.peer

    # -- service table -----------------------------------------------------
    def add_service(self, service) -> int:
        service_id = next(self._service_counter)
        self._services[service_id] = service
        # assign the address here: service_fields() (used for registrar
        # registration below) needs topic_path before Service.__init__ has
        # returned
        service.service_id = service_id
        service.topic_path = f"{self.topic_path}/{service_id}"
        if self.peer is not None and self.peer.tag not in service.tags:
            # every service of a peer-enabled runtime advertises the
            # direct-channel endpoint in its discovery record
            service.tags.append(self.peer.tag)
        if self.registrar is not None:
            self._register_service(service)
        return service_id

    def remove_service(self, service_id: int) -> None:
        service = self._services.pop(service_id, None)
        if service is not None and self.registrar is not None and \
                self.message is not None and self.message.connected():
            self.publish(f"{self.registrar['topic_path']}/in",
                         generate("remove", [service.topic_path]))

    def services(self):
        return dict(self._services)

    def service_by_name(self, name: str):
        for service in self._services.values():
            if service.name == name:
                return service
        return None

    # -- registrar bootstrap ----------------------------------------------
    def add_registrar_handler(self, handler) -> None:
        """handler(registrar_or_None) on found/absent; fired with current."""
        self._registrar_handlers.append(handler)
        handler(self.registrar)

    def _register_service(self, service) -> None:
        fields = service.service_fields()
        self.publish(
            f"{self.registrar['topic_path']}/in",
            generate("add", fields.to_record()))

    def _on_registrar(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "primary" and len(params) >= 2 and \
                params[0] == "found":
            self.registrar = {
                "topic_path": params[1],
                "version": params[2] if len(params) > 2 else "0",
                "timestamp": params[3] if len(params) > 3 else "0",
            }
            for service in self._services.values():
                self._register_service(service)
            self.connection.update(ConnectionState.REGISTRAR)
        elif command == "primary" and params and params[0] == "absent":
            self.registrar = None
            if self.connection.state >= ConnectionState.REGISTRAR:
                self.connection.update(ConnectionState.TRANSPORT)
            if self.terminate_on_registrar_absent:
                self.event.terminate()
        for handler in list(self._registrar_handlers):
            handler(self.registrar)
