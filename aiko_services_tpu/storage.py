# Storage: persistent key/value actor + the discover-call-respond request
# pattern.
#
# Capability parity with the reference storage service
# (reference: aiko_services/storage.py:39-146): sqlite-backed actor with a
# command API, plus do_command/do_request — the client-side pattern of
# discovering a service by filter, proxying a call at it, and (for
# requests) collecting an `(item_count N)`-prefixed response stream on a
# private response topic.

from __future__ import annotations

import json
import sqlite3

from .actor import Actor, ActorDiscovery, get_remote_proxy
from .service import ServiceFilter, ServiceProtocol
from .utils import generate, get_logger, parse, parse_int

__all__ = ["Storage", "PROTOCOL_STORAGE", "do_command", "do_request",
           "ResponseCollector"]

PROTOCOL_STORAGE = ServiceProtocol("storage")


class Storage(Actor):
    """Key/value store: `(put key value)`, `(get key response_topic)`,
    `(delete key)`, `(keys response_topic)`.  Values are JSON strings."""

    def __init__(self, runtime, name: str = "storage",
                 database_path: str = ":memory:"):
        super().__init__(runtime, name, PROTOCOL_STORAGE)
        self.logger = get_logger(f"storage.{name}")
        self.connection = sqlite3.connect(database_path)
        self.connection.execute(
            "CREATE TABLE IF NOT EXISTS store "
            "(key TEXT PRIMARY KEY, value TEXT)")
        self.ec_producer.update("database", database_path)

    def put(self, key, value) -> None:
        self.connection.execute(
            "INSERT INTO store (key, value) VALUES (?, ?) "
            "ON CONFLICT(key) DO UPDATE SET value = excluded.value",
            (str(key), json.dumps(value)))
        self.connection.commit()

    def get(self, key, response_topic) -> None:
        row = self.connection.execute(
            "SELECT value FROM store WHERE key = ?",
            (str(key),)).fetchone()
        items = [json.loads(row[0])] if row else []
        self._respond(response_topic, items)

    def delete(self, key) -> None:
        self.connection.execute("DELETE FROM store WHERE key = ?",
                                (str(key),))
        self.connection.commit()

    def keys(self, response_topic) -> None:
        rows = self.connection.execute(
            "SELECT key FROM store ORDER BY key").fetchall()
        self._respond(response_topic, [r[0] for r in rows])

    def _respond(self, response_topic, items) -> None:
        self.runtime.publish(response_topic,
                             generate("item_count", [str(len(items))]))
        for item in items:
            self.runtime.publish(response_topic,
                                 generate("item", [json.dumps(item)]))

    def stop(self) -> None:
        self.connection.close()
        super().stop()


class ResponseCollector:
    """Collects an `(item_count N)` + `(item ...)`* response stream on a
    private topic (the reference's request half, storage.py:68-104)."""

    _counter = 0

    def __init__(self, runtime, handler):
        ResponseCollector._counter += 1
        self.runtime = runtime
        self.handler = handler           # handler(items: list)
        self.topic = (f"{runtime.topic_path}/0/response/"
                      f"{ResponseCollector._counter}")
        self.expected = None
        self.items: list = []
        runtime.add_message_handler(self._on_message, self.topic)

    def _on_message(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "item_count" and params:
            self.expected = parse_int(params[0], 0)
            if self.expected == 0:
                self._finish()
        elif command == "item" and params:
            self.items.append(json.loads(params[0]))
            if self.expected is not None and \
                    len(self.items) >= self.expected:
                self._finish()

    def _finish(self) -> None:
        self.runtime.remove_message_handler(self._on_message, self.topic)
        self.handler(self.items)


def do_command(runtime, protocol_class, service_filter: ServiceFilter,
               command_handler, discovery: ActorDiscovery | None = None):
    """Discover one service matching `service_filter`, build a proxy, and
    invoke command_handler(proxy) exactly once (reference: storage.py
    do_command)."""
    discovery = discovery or ActorDiscovery(runtime)
    fired = []

    def on_change(command, fields):
        if command == "add" and not fired:
            fired.append(fields)
            proxy = get_remote_proxy(runtime, f"{fields.topic_path}/in",
                                     protocol_class)
            command_handler(proxy)

    discovery.add_handler(on_change, service_filter)
    return discovery


def do_request(runtime, protocol_class, service_filter: ServiceFilter,
               request_handler, response_handler,
               discovery: ActorDiscovery | None = None):
    """do_command + a ResponseCollector: request_handler(proxy, topic)
    issues the call with the private response topic; response_handler
    receives the collected items."""
    collector = ResponseCollector(runtime, response_handler)

    def command_handler(proxy):
        request_handler(proxy, collector.topic)

    return do_command(runtime, protocol_class, service_filter,
                      command_handler, discovery)
