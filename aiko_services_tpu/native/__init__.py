# Native extension loader: compiles aiko_native.cpp (a CPython extension
# module) on first use with g++, caches the .so next to the source, and
# degrades gracefully when no toolchain is present — callers keep their
# pure-Python fallbacks.
#
# Disable with AIKO_TPU_NATIVE=0 (e.g. to benchmark the fallbacks).

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig

__all__ = ["load", "native_topic_matches", "native_parse_sexpr",
           "NATIVE_AVAILABLE"]

_here = os.path.dirname(os.path.abspath(_file_ := __file__))
_source = os.path.join(_here, "aiko_native.cpp")
_module = None
_load_attempted = False


def _build_path() -> str:
    tag = sysconfig.get_config_var("SOABI") or "native"
    return os.path.join(_here, f"_aiko_native.{tag}.so")


def load():
    """Compile (if needed) and import the extension; None on failure."""
    global _module, _load_attempted
    if _module is not None or _load_attempted:
        return _module
    _load_attempted = True
    if os.environ.get("AIKO_TPU_NATIVE", "1") == "0":
        return None
    so_path = _build_path()
    try:
        if not os.path.exists(so_path) or \
                os.path.getmtime(so_path) < os.path.getmtime(_source):
            include = sysconfig.get_path("include")
            # Compile to a private temp name and rename into place so
            # concurrent processes never import a half-written .so
            # (rename is atomic on POSIX; last writer wins).
            tmp_path = f"{so_path}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", f"-I{include}",
                     "-o", tmp_path, _source],
                    check=True, capture_output=True, timeout=180)
                os.rename(tmp_path, so_path)
            finally:
                if os.path.exists(tmp_path):
                    os.unlink(tmp_path)
        spec = importlib.util.spec_from_file_location("_aiko_native",
                                                      so_path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        from ..utils.sexpr import ParseError
        module.set_parse_error(ParseError)
        _module = module
    except Exception:
        _module = None
    return _module


def native_topic_matches(pattern: str, topic: str) -> bool:
    module = load()
    if module is None:
        raise RuntimeError("native extension unavailable")
    return module.topic_matches(pattern, topic)


def native_parse_sexpr(payload: str):
    """Parse via the C extension.  Raises RuntimeError for payloads the
    native path does not cover (non-ASCII: length prefixes count
    characters, the native scanner counts bytes)."""
    module = load()
    if module is None:
        raise RuntimeError("native extension unavailable")
    if not payload.isascii():
        raise RuntimeError("non-ascii payload")
    return module.parse_sexpr(payload)


NATIVE_AVAILABLE = load() is not None
