// Native host-path kernels for the control plane — CPython extension.
//
// Every control-plane message crosses topic_matches() (wildcard routing,
// process.py) and the S-expression parser (utils/sexpr.py); at the
// reference's stated scale goal (1k-10k services/process, reference:
// aiko_services/process.py:45-48) these dominate host CPU.  A CPython
// extension (not ctypes: per-call marshalling erases the win) builds the
// parse tree directly as Python objects.  utils/sexpr.py keeps an
// identical pure-Python fallback; tests/test_native.py asserts parity.
//
// Built on demand by native/__init__.py:
//   g++ -O2 -shared -fPIC -I<python-include> aiko_native.cpp

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <vector>

// ---------------------------------------------------------------------------
// topic matching (parity: transport/message.py _py_topic_matches)
// ---------------------------------------------------------------------------

static bool topic_matches_impl(const char *pattern, const char *topic) {
    if (strcmp(pattern, topic) == 0) return true;
    const char *p = pattern, *t = topic;
    bool t_exhausted = false;
    for (;;) {
        const char *pe = p;
        while (*pe && *pe != '/') pe++;
        if (pe - p == 1 && *p == '#') return true;
        if (t_exhausted) return false;      // pattern longer than topic
        const char *te = t;
        while (*te && *te != '/') te++;
        if (!(pe - p == 1 && *p == '+')) {
            if ((pe - p) != (te - t) || strncmp(p, t, pe - p) != 0)
                return false;
        }
        bool p_end = (*pe == '\0');
        bool t_end = (*te == '\0');
        if (p_end) return t_end;
        p = pe + 1;
        if (t_end) { t_exhausted = true; } else { t = te + 1; }
    }
}

static PyObject *py_topic_matches(PyObject *, PyObject *args) {
    const char *pattern, *topic;
    if (!PyArg_ParseTuple(args, "ss", &pattern, &topic)) return nullptr;
    return PyBool_FromLong(topic_matches_impl(pattern, topic));
}

// ---------------------------------------------------------------------------
// S-expression parser (parity: utils/sexpr.py parse_sexpr)
// ---------------------------------------------------------------------------

static PyObject *parse_error;       // set from Python (sexpr.ParseError)

struct Token {
    char kind;          // '(', ')', 'A' atom, 'R' raw (length-prefixed)
    Py_ssize_t start;
    Py_ssize_t end;
};

static bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\n';
}

// returns false + sets parse_error on overrun
static bool tokenize_impl(const char *text, Py_ssize_t n,
                          std::vector<Token> &tokens) {
    Py_ssize_t i = 0;
    while (i < n) {
        char ch = text[i];
        if (is_space(ch)) { i++; continue; }
        if (ch == '(' || ch == ')') {
            tokens.push_back({ch, i, i + 1});
            i++;
            continue;
        }
        Py_ssize_t j = i;
        bool emitted = false;
        while (j < n) {
            char cj = text[j];
            if (cj == '(' || cj == ')' || is_space(cj)) break;
            if (cj == ':' && j > i) {
                bool all_digits = true;
                for (Py_ssize_t k = i; k < j; k++)
                    if (text[k] < '0' || text[k] > '9') {
                        all_digits = false;
                        break;
                    }
                if (all_digits) {
                    long long length = 0;
                    for (Py_ssize_t k = i; k < j; k++)
                        length = length * 10 + (text[k] - '0');
                    Py_ssize_t start = j + 1;
                    if (start + (Py_ssize_t)length > n) {
                        PyErr_SetString(
                            parse_error ? parse_error : PyExc_ValueError,
                            "length-prefixed token overruns payload");
                        return false;
                    }
                    tokens.push_back({'R', start,
                                      start + (Py_ssize_t)length});
                    i = start + (Py_ssize_t)length;
                    emitted = true;
                    break;
                }
            }
            j++;
        }
        if (emitted) continue;
        tokens.push_back({'A', i, j});
        i = j;
    }
    return true;
}

// dict-key test: plain atom (not raw), ends with ':', length > 1
static bool is_dict_key(const char *text, const Token &token) {
    if (token.kind != 'A') return false;
    Py_ssize_t length = token.end - token.start;
    return length > 1 && text[token.end - 1] == ':';
}

// group close: convert items (+ their tokens) to dict when they form
// "key: value" pairs (parity: sexpr._maybe_dict)
static PyObject *maybe_dict(const char *text, PyObject *items,
                            const std::vector<char> &kinds,
                            const std::vector<Token> &key_tokens) {
    Py_ssize_t count = PyList_GET_SIZE(items);
    if (count == 0 || count % 2) {
        Py_INCREF(items);
        return items;
    }
    for (Py_ssize_t i = 0; i < count; i += 2) {
        // keys must be atom strings flagged as dict keys
        if (kinds[i] != 'K') {
            Py_INCREF(items);
            return items;
        }
    }
    (void)text; (void)key_tokens;
    PyObject *dict = PyDict_New();
    if (!dict) return nullptr;
    for (Py_ssize_t i = 0; i < count; i += 2) {
        PyObject *key_full = PyList_GET_ITEM(items, i);   // "name:"
        Py_ssize_t key_length;
        const char *key_text = PyUnicode_AsUTF8AndSize(key_full,
                                                       &key_length);
        if (!key_text) { Py_DECREF(dict); return nullptr; }
        PyObject *key = PyUnicode_FromStringAndSize(key_text,
                                                    key_length - 1);
        if (!key) { Py_DECREF(dict); return nullptr; }
        if (PyDict_SetItem(dict, key,
                           PyList_GET_ITEM(items, i + 1)) < 0) {
            Py_DECREF(key);
            Py_DECREF(dict);
            return nullptr;
        }
        Py_DECREF(key);
    }
    return dict;
}

// recursive reader over the token stream
static PyObject *read_expr(const char *text,
                           const std::vector<Token> &tokens,
                           size_t &pos, char *out_kind) {
    const Token &token = tokens[pos];
    if (token.kind == '(') {
        pos++;
        PyObject *items = PyList_New(0);
        if (!items) return nullptr;
        std::vector<char> kinds;
        std::vector<Token> item_tokens;
        while (pos < tokens.size() && tokens[pos].kind != ')') {
            char kind = 0;
            Token item_token = tokens[pos];
            PyObject *item = read_expr(text, tokens, pos, &kind);
            if (!item) { Py_DECREF(items); return nullptr; }
            if (PyList_Append(items, item) < 0) {
                Py_DECREF(item);
                Py_DECREF(items);
                return nullptr;
            }
            Py_DECREF(item);
            kinds.push_back(kind);
            item_tokens.push_back(item_token);
        }
        if (pos >= tokens.size()) {
            Py_DECREF(items);
            PyErr_SetString(parse_error ? parse_error : PyExc_ValueError,
                            "unbalanced '(' in payload");
            return nullptr;
        }
        pos++;      // consume ')'
        PyObject *result = maybe_dict(text, items, kinds, item_tokens);
        Py_DECREF(items);
        *out_kind = 'G';
        return result;
    }
    if (token.kind == ')') {
        PyErr_SetString(parse_error ? parse_error : PyExc_ValueError,
                        "unbalanced ')' in payload");
        return nullptr;
    }
    pos++;
    *out_kind = (token.kind == 'A' && is_dict_key(text, token)) ? 'K'
                : token.kind;        // 'A' plain, 'R' raw, 'K' dict key
    return PyUnicode_FromStringAndSize(text + token.start,
                                       token.end - token.start);
}

static PyObject *py_parse_sexpr(PyObject *, PyObject *args) {
    const char *text;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "s#", &text, &n)) return nullptr;

    std::vector<Token> tokens;
    if (!tokenize_impl(text, n, tokens)) return nullptr;
    if (tokens.empty()) return PyList_New(0);

    size_t pos = 0;
    char kind = 0;
    PyObject *expr = read_expr(text, tokens, pos, &kind);
    if (!expr) return nullptr;
    if (pos != tokens.size()) {
        Py_DECREF(expr);
        PyErr_SetString(parse_error ? parse_error : PyExc_ValueError,
                        "trailing tokens after expression");
        return nullptr;
    }
    return expr;
}

static PyObject *py_set_parse_error(PyObject *, PyObject *args) {
    PyObject *exc;
    if (!PyArg_ParseTuple(args, "O", &exc)) return nullptr;
    Py_XINCREF(exc);
    Py_XDECREF(parse_error);
    parse_error = exc;
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"topic_matches", py_topic_matches, METH_VARARGS,
     "MQTT-style wildcard topic match"},
    {"parse_sexpr", py_parse_sexpr, METH_VARARGS,
     "Parse an S-expression payload into nested lists/dicts"},
    {"set_parse_error", py_set_parse_error, METH_VARARGS,
     "Install the ParseError exception class"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "_aiko_native",
    "Native control-plane kernels", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

PyMODINIT_FUNC PyInit__aiko_native(void) {
    return PyModule_Create(&module_def);
}
