# Legacy stream-element compatibility shim.
#
# Capability parity with the reference's 2020-era pipeline API
# (reference: aiko_services/pipeline_2020.py:31-259 + stream_2020.py:19-68
# — StreamElement subclasses with stream_start_handler /
# stream_frame_handler / stream_stop_handler and a START/RUN/STOP
# lifecycle).  Elements written against that API run unchanged on the
# modern engine through this adapter; new code should subclass
# PipelineElement directly.

from __future__ import annotations

from .pipeline import Frame, FrameOutput, PipelineElement, Stream

__all__ = ["StreamElement", "StreamElementState"]


class StreamElementState:
    START = "start"
    RUN = "run"
    STOP = "stop"
    COMPLETE = "complete"


class StreamElement(PipelineElement):
    """2020-API adapter: implement the three *_handler methods, each
    returning (ok, swag_update)."""

    def get_state(self, stream: Stream) -> str:
        return stream.variables.get(f"{self.definition.name}.state2020",
                                    StreamElementState.START)

    def _set_state(self, stream: Stream, state: str) -> None:
        stream.variables[f"{self.definition.name}.state2020"] = state

    # -- legacy handler surface (override these) ---------------------------
    def stream_start_handler(self, stream, stream_id):
        return True, {}

    def stream_frame_handler(self, stream, frame_id, swag):
        return True, {}

    def stream_stop_handler(self, stream, stream_id):
        return True, {}

    # -- modern engine mapping ---------------------------------------------
    def start_stream(self, stream: Stream) -> None:
        self._set_state(stream, StreamElementState.START)
        ok, _ = self.stream_start_handler(stream, stream.stream_id)
        if not ok:
            raise RuntimeError(
                f"{self.definition.name}: stream_start_handler failed")
        self._set_state(stream, StreamElementState.RUN)

    def process_frame(self, frame: Frame, **inputs) -> FrameOutput:
        swag = dict(frame.swag)
        swag.update(inputs)
        ok, update = self.stream_frame_handler(frame.stream,
                                               frame.frame_id, swag)
        return FrameOutput(ok, update or {})

    def stop_stream(self, stream: Stream) -> None:
        self._set_state(stream, StreamElementState.STOP)
        self.stream_stop_handler(stream, stream.stream_id)
        self._set_state(stream, StreamElementState.COMPLETE)
