# Recorder: aggregate distributed log topics into browsable ring buffers.
#
# Capability parity with the reference recorder
# (reference: aiko_services/recorder.py:43-107): subscribes the namespace
# log topic filter ({namespace}/+/+/+/log), keeps an LRU of per-topic ring
# buffers, and republishes counts into its EC share so dashboards can
# discover which services are logging and fetch their tails.

from __future__ import annotations

from collections import deque

from .actor import Actor
from .service import ServiceProtocol
from .utils import LRUCache, get_logger

__all__ = ["Recorder", "PROTOCOL_RECORDER"]

PROTOCOL_RECORDER = ServiceProtocol("recorder")
_TOPIC_LIMIT = 64           # LRU of log topics
_RING_LIMIT = 128           # records per topic


class Recorder(Actor):
    def __init__(self, runtime, name: str = "recorder",
                 topic_limit: int = _TOPIC_LIMIT,
                 ring_limit: int = _RING_LIMIT):
        super().__init__(runtime, name, PROTOCOL_RECORDER)
        self.logger = get_logger("recorder")
        self.ring_limit = ring_limit
        self.buffers: LRUCache = LRUCache(topic_limit)
        self._log_filter = f"{runtime.namespace}/+/+/+/log"
        runtime.add_message_handler(self._log_handler, self._log_filter)
        self.ec_producer.update("topic_count", 0)
        self.ec_producer.update("record_count", 0)

    def _log_handler(self, topic: str, payload) -> None:
        ring = self.buffers.get(topic)
        if ring is None:
            ring = deque(maxlen=self.ring_limit)
            self.buffers.put(topic, ring)
            self.ec_producer.update("topic_count", len(self.buffers))
        ring.append(payload)
        total = sum(len(self.buffers.get(t)) for t in self.buffers.keys())
        self.ec_producer.update("record_count", total)

    def tail(self, topic: str, count: int = 16) -> list:
        ring = self.buffers.get(topic)
        return list(ring)[-count:] if ring else []

    def topics(self) -> list[str]:
        return list(self.buffers.keys())

    def persist(self, storage_topic_in: str) -> None:
        """Write every ring durably to a Storage service (sqlite) as
        `log/<topic>` → record list, over the standard `(put ...)` RPC —
        the persistence the reference recorder aspired to but never
        built (reference recorder.py ring buffers are memory-only).
        Callable remotely: publish `(persist <storage_topic_in>)` to
        this recorder's in topic.

        Binary records (bytes from binary log topics) are persisted as
        latin-1 text — lossless byte mapping, not a Python repr."""
        from .actor import get_remote_proxy
        from .storage import Storage

        storage = get_remote_proxy(self.runtime, str(storage_topic_in),
                                   Storage)
        for topic in self.buffers.keys():
            records = [record.decode("latin-1")
                       if isinstance(record, bytes) else str(record)
                       for record in self.buffers.get(topic)]
            storage.put(f"log/{topic}", records)
        self.ec_producer.update("persisted_topics", len(self.buffers))

    def stop(self) -> None:
        self.runtime.remove_message_handler(self._log_handler,
                                            self._log_filter)
        super().stop()
