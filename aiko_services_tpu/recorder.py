# Recorder: aggregate distributed log topics into browsable ring buffers.
#
# Capability parity with the reference recorder
# (reference: aiko_services/recorder.py:43-107): subscribes the namespace
# log topic filter ({namespace}/+/+/+/log), keeps an LRU of per-topic ring
# buffers, and republishes counts into its EC share so dashboards can
# discover which services are logging and fetch their tails.
#
# Metrics page (ISSUE 9, the PR 5 follow-up): the same discipline for the
# retained {topic_path}/0/metrics snapshots every MetricsPublisher emits —
# the dashboard's 'm' pane renders the LOCAL registry; the Recorder is
# what captures REMOTE processes' snapshots, browsable live
# (metrics_tail) and persistable to Storage beside the log rings.

from __future__ import annotations

from collections import deque

from .actor import Actor
from .observe.export import METRICS_TOPIC_SUFFIX, parse_retained_json
from .observe.series import ALERT_TOPIC_PREFIX
from .service import ServiceProtocol
from .utils import LRUCache, get_logger

__all__ = ["Recorder", "PROTOCOL_RECORDER"]

PROTOCOL_RECORDER = ServiceProtocol("recorder")
_TOPIC_LIMIT = 64           # LRU of log topics
_RING_LIMIT = 128           # records per topic
_METRICS_RING_LIMIT = 8     # snapshots kept per metrics topic (each is
                            # a full registry dump — deep history is the
                            # scraper's job, the tail is the Recorder's)


class Recorder(Actor):
    def __init__(self, runtime, name: str = "recorder",
                 topic_limit: int = _TOPIC_LIMIT,
                 ring_limit: int = _RING_LIMIT,
                 metrics_ring_limit: int = _METRICS_RING_LIMIT):
        super().__init__(runtime, name, PROTOCOL_RECORDER)
        self.logger = get_logger("recorder")
        self.ring_limit = ring_limit
        self.metrics_ring_limit = metrics_ring_limit
        self.buffers: LRUCache = LRUCache(topic_limit)
        self.metrics_buffers: LRUCache = LRUCache(topic_limit)
        self._log_filter = f"{runtime.namespace}/+/+/+/log"
        runtime.add_message_handler(self._log_handler, self._log_filter)
        # topic_path is {namespace}/{host}/{pid}; snapshots ride
        # {topic_path}/0/metrics (observe/export.py MetricsPublisher) —
        # retained, so a late-started Recorder still catches the latest
        self._metrics_filter = \
            f"{runtime.namespace}/+/+/{METRICS_TOPIC_SUFFIX}"
        runtime.add_message_handler(self._metrics_handler,
                                    self._metrics_filter)
        # SLO alert records (ISSUE 11): HealthAggregator publishes
        # retained {namespace}/alert/{rule} — the Recorder keeps the
        # latest record per rule so a late-joining operator (or the
        # Dashboard through the Recorder's EC share) sees what fired
        self.alerts: dict[str, dict] = {}
        self._alert_filter = \
            f"{runtime.namespace}/{ALERT_TOPIC_PREFIX}/+"
        runtime.add_message_handler(self._alert_handler,
                                    self._alert_filter)
        self.ec_producer.update("topic_count", 0)
        self.ec_producer.update("record_count", 0)
        self.ec_producer.update("metrics_topic_count", 0)
        self.ec_producer.update("alerts_firing", 0)

    def _log_handler(self, topic: str, payload) -> None:
        ring = self.buffers.get(topic)
        if ring is None:
            ring = deque(maxlen=self.ring_limit)
            self.buffers.put(topic, ring)
            self.ec_producer.update("topic_count", len(self.buffers))
        ring.append(payload)
        total = sum(len(self.buffers.get(t)) for t in self.buffers.keys())
        self.ec_producer.update("record_count", total)

    def _metrics_handler(self, topic: str, payload) -> None:
        document = parse_retained_json(payload)
        if document is None:
            self.logger.debug("recorder: unparseable metrics snapshot "
                              "on %s", topic)
            return
        ring = self.metrics_buffers.get(topic)
        if ring is None:
            ring = deque(maxlen=self.metrics_ring_limit)
            self.metrics_buffers.put(topic, ring)
            self.ec_producer.update("metrics_topic_count",
                                    len(self.metrics_buffers))
        ring.append(document)

    def _alert_handler(self, topic: str, payload) -> None:
        record = parse_retained_json(payload, require_key="rule")
        if record is None:
            self.logger.debug("recorder: unparseable alert record on "
                              "%s", topic)
            return
        # keyed by fleet SLO rule names — bounded:
        # graft: disable=lint-unbounded-cache
        self.alerts[str(record["rule"])] = record
        self.ec_producer.update("alerts_firing", sum(
            1 for entry in self.alerts.values()
            if entry.get("state") == "firing"))

    def alert_records(self) -> dict:
        """Latest alert record per rule (firing or resolved)."""
        return dict(self.alerts)

    def alert_exemplars(self) -> dict:
        """Exemplar trace ids per FIRING rule (ISSUE 12): the requests
        behind each breaching quantile — the ids to grep a flight dump
        (or this recorder's log rings) for."""
        return {rule: list(record.get("exemplars", []))
                for rule, record in self.alerts.items()
                if record.get("state") == "firing"
                and record.get("exemplars")}

    def tail(self, topic: str, count: int = 16) -> list:
        ring = self.buffers.get(topic)
        return list(ring)[-count:] if ring else []

    def topics(self) -> list[str]:
        return list(self.buffers.keys())

    def metrics_tail(self, topic: str, count: int = 1) -> list:
        """The last `count` captured snapshot documents of one metrics
        topic (parsed: {"process", "topic_path", "time", "snapshot"})."""
        ring = self.metrics_buffers.get(topic)
        return list(ring)[-count:] if ring else []

    def metrics_topics(self) -> list[str]:
        return list(self.metrics_buffers.keys())

    def persist(self, storage_topic_in: str) -> None:
        """Write every ring durably to a Storage service (sqlite) as
        `log/<topic>` → record list and `metrics/<topic>` → snapshot
        list, over the standard `(put ...)` RPC — the persistence the
        reference recorder aspired to but never built (reference
        recorder.py ring buffers are memory-only).  Callable remotely:
        publish `(persist <storage_topic_in>)` to this recorder's in
        topic.

        Binary records (bytes from binary log topics) are persisted as
        latin-1 text — lossless byte mapping, not a Python repr."""
        from .actor import get_remote_proxy
        from .storage import Storage

        storage = get_remote_proxy(self.runtime, str(storage_topic_in),
                                   Storage)
        for topic in self.buffers.keys():
            records = [record.decode("latin-1")
                       if isinstance(record, bytes) else str(record)
                       for record in self.buffers.get(topic)]
            storage.put(f"log/{topic}", records)
        for topic in self.metrics_buffers.keys():
            storage.put(f"metrics/{topic}",
                        list(self.metrics_buffers.get(topic)))
        self.ec_producer.update("persisted_topics", len(self.buffers))
        self.ec_producer.update("persisted_metrics_topics",
                                len(self.metrics_buffers))

    def stop(self) -> None:
        self.runtime.remove_message_handler(self._log_handler,
                                            self._log_filter)
        self.runtime.remove_message_handler(self._metrics_handler,
                                            self._metrics_filter)
        self.runtime.remove_message_handler(self._alert_handler,
                                            self._alert_filter)
        super().stop()
