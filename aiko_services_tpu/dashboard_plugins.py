# Built-in dashboard plugin pages (reference: dashboard_plugins.py —
# extra rendering for known service protocols).  TPU-native additions:
# the ComputeRuntime page surfaces device health and batching stats, the
# placement/lifecycle page surfaces pool occupancy — the "device health
# next to process health" obligation (SURVEY §7 two-plane consistency).

from __future__ import annotations

from .dashboard import register_plugin


def _flat(state) -> dict:
    return dict(state.flat_share())


def render_compute(state, fields) -> list:
    share = _flat(state)
    lines = [f"devices: {share.get('device_count', '?')} "
             f"({share.get('platform', '?')}/"
             f"{share.get('device_kind', '?')})  "
             f"programs: {share.get('program_count', '?')}"]
    mesh = {key.split(".", 1)[1]: value for key, value in share.items()
            if key.startswith("mesh.")}
    if mesh:
        lines.append("mesh: " + " × ".join(f"{k}={v}"
                                           for k, v in mesh.items()))
    for key in sorted(share):
        if key.startswith("device.") and key.endswith(".mem_pct"):
            device_id = key.split(".")[1]
            value = share[key]
            mem = "n/a" if value == -1 else f"{value}%"
            lines.append(f"  device {device_id}: mem {mem}")
    for key in sorted(share):
        if key.startswith("batch.") and key.endswith(".mean_size"):
            # program names themselves contain dots (agent.PE_X)
            program = key[len("batch."):-len(".mean_size")]
            wait = share.get(f"batch.{program}.mean_wait_ms", "?")
            count = share.get(f"batch.{program}.batches", "?")
            lines.append(f"  {program}: {count} batches, "
                         f"mean size {share[key]}, wait {wait} ms")
    return lines


def render_lifecycle_manager(state, fields) -> list:
    share = _flat(state)
    lines = [f"clients: {share.get('client_count', '?')}"]
    if "devices_total" in share:
        lines.append(f"device pool: "
                     f"{share.get('devices_allocated', 0)} allocated / "
                     f"{share.get('devices_free', 0)} free of "
                     f"{share.get('devices_total', 0)}")
    for key in sorted(share):
        if key.startswith("placement."):
            lines.append(f"  client {key.split('.', 1)[1]}: {share[key]}")
    return lines


def register_builtins() -> None:
    """(Re-)register the shipped plugin pages.  Re-runnable on purpose:
    import side effects are one-shot, and a test (or embedder) that
    clears the plugin table could otherwise never get these back."""
    register_plugin("compute", render_compute)
    register_plugin("lifecycle_manager", render_lifecycle_manager)


register_builtins()
