# ProcessManager: spawn and supervise OS child processes.
#
# Capability parity with the reference process manager
# (reference: aiko_services/process_manager.py:48-187): Popen-based child
# table keyed by caller id, command/module path resolution, periodic child
# polling, exit-handler callback with (id, pid, return_code).
#
# Design changes: polling rides the EventEngine (no dedicated thread, so
# tests drive it deterministically), and a `spawn_python` helper launches
# module targets with the current interpreter.

from __future__ import annotations

import shlex
import subprocess
import sys

from .utils import get_logger

__all__ = ["ProcessManager"]

_POLL_PERIOD = 0.2      # seconds (reference: process_manager.py:102)


class ProcessManager:
    def __init__(self, engine, process_exit_handler=None,
                 poll_period: float = _POLL_PERIOD):
        self.engine = engine
        self.process_exit_handler = process_exit_handler
        self.logger = get_logger("process_manager")
        self.processes: dict[str, subprocess.Popen] = {}
        self._timer = engine.add_timer_handler(self._poll, poll_period)

    def spawn(self, id, command, arguments=(), **popen_kwargs) -> int:
        """Launch `command arguments...`; returns the OS pid."""
        id = str(id)
        if id in self.processes:
            raise ValueError(f"process id exists: {id}")
        if isinstance(command, str):
            argv = shlex.split(command) + [str(a) for a in arguments]
        else:
            argv = list(command) + [str(a) for a in arguments]
        process = subprocess.Popen(argv, **popen_kwargs)
        self.processes[id] = process
        self.logger.info("spawned %s: pid %s: %s", id, process.pid,
                         " ".join(argv))
        return process.pid

    def spawn_python(self, id, module: str, arguments=(), **popen_kwargs):
        """Launch `python -m module args...` with this interpreter."""
        return self.spawn(id, [sys.executable, "-m", module], arguments,
                          **popen_kwargs)

    def delete(self, id, kill: bool = True, timeout: float = 5.0) -> None:
        process = self.processes.pop(str(id), None)
        if process is None:
            return
        if kill and process.poll() is None:
            process.terminate()
            try:
                process.wait(timeout)
            except subprocess.TimeoutExpired:
                process.kill()
                process.wait()

    def process_ids(self):
        return list(self.processes)

    def __contains__(self, id):
        return str(id) in self.processes

    def _poll(self) -> None:
        for id, process in list(self.processes.items()):
            return_code = process.poll()
            if return_code is None:
                continue
            del self.processes[id]
            self.logger.info("process %s (pid %s) exited: %s", id,
                             process.pid, return_code)
            if self.process_exit_handler:
                try:
                    self.process_exit_handler(id, process.pid, return_code)
                except Exception:
                    self.logger.exception("exit handler raised for %s", id)

    def terminate(self, kill_children: bool = True) -> None:
        self.engine.remove_timer_handler(self._timer)
        for id in list(self.processes):
            self.delete(id, kill=kill_children)
