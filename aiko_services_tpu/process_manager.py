# ProcessManager: spawn and supervise OS child processes.
#
# Capability parity with the reference process manager
# (reference: aiko_services/process_manager.py:48-187): Popen-based child
# table keyed by caller id, command/module path resolution, periodic child
# polling, exit-handler callback with (id, pid, return_code).
#
# Design changes: polling rides the EventEngine (no dedicated thread, so
# tests drive it deterministically), a `spawn_python` helper launches
# module targets with the current interpreter, and spawns may carry a
# RestartPolicy (ISSUE 4): exponential backoff + seeded jitter between
# respawns, a crash-loop detector (too many restarts inside a sliding
# window gives up instead of thrashing), all timed on the engine clock so
# supervision is deterministic under a VirtualClock.

from __future__ import annotations

import random
import shlex
import subprocess
import sys
from collections import deque
from dataclasses import dataclass

from .utils import get_logger, jittered_backoff

__all__ = ["ProcessManager", "RestartPolicy", "RestartWindow"]

_POLL_PERIOD = 0.2      # seconds (reference: process_manager.py:102)


@dataclass(frozen=True)
class RestartPolicy:
    """Supervision policy for a spawned child.

    max_restarts restarts inside `window` seconds is a crash loop: the
    supervisor stops respawning and reports through crash_loop_handler /
    process_exit_handler instead of thrashing the host.  Backoff doubles
    per consecutive restart inside the window and carries jitter so a
    fleet of supervisors does not stampede — seed=None (default) spreads
    for real; pass a seed for reproducible tests."""
    max_restarts: int = 3
    window: float = 60.0            # seconds, crash-loop detection span
    backoff: float = 0.5            # first respawn delay
    backoff_max: float = 30.0
    jitter: float = 0.25            # fraction of the delay
    restart_on_success: bool = False    # also respawn rc == 0 exits
    seed: int | None = None         # None = urandom (deterministic opt-in)

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        """Jittered exponential backoff for the attempt-th restart."""
        return jittered_backoff(self.backoff, attempt, self.backoff_max,
                                self.jitter, rng)


class RestartWindow:
    """Sliding-window crash-loop accounting, shared by ProcessManager
    (per-child) and LifeCycleManager (per-fleet): record() a death and
    get back the respawn delay, or None once the window budget is spent
    (crash loop — stop respawning)."""

    def __init__(self, policy: RestartPolicy):
        self.policy = policy
        self.events: deque[float] = deque()     # engine-clock death times
        self.rng = random.Random(policy.seed)

    def record(self, now: float) -> float | None:
        self.events.append(now)
        while self.events and now - self.events[0] > self.policy.window:
            self.events.popleft()
        if len(self.events) > self.policy.max_restarts:
            return None
        return self.policy.delay_for(len(self.events), self.rng)


class _Supervised:
    """Restart bookkeeping for one managed id."""
    __slots__ = ("argv", "popen_kwargs", "policy", "window",
                 "pending_timer", "crash_looping")

    def __init__(self, argv, popen_kwargs, policy: RestartPolicy):
        self.argv = argv
        self.popen_kwargs = popen_kwargs
        self.policy = policy
        self.window = RestartWindow(policy)
        self.pending_timer = None
        self.crash_looping = False


class ProcessManager:
    def __init__(self, engine, process_exit_handler=None,
                 poll_period: float = _POLL_PERIOD,
                 crash_loop_handler=None):
        self.engine = engine
        self.process_exit_handler = process_exit_handler
        # crash_loop_handler(id, exit_times) when supervision gives up
        self.crash_loop_handler = crash_loop_handler
        self.logger = get_logger("process_manager")
        self.processes: dict[str, subprocess.Popen] = {}
        self._supervised: dict[str, _Supervised] = {}
        self._timer = engine.add_timer_handler(self._poll, poll_period)

    def spawn(self, id, command, arguments=(),
              restart: RestartPolicy | None = None, **popen_kwargs) -> int:
        """Launch `command arguments...`; returns the OS pid.  With a
        RestartPolicy the child is supervised: exits respawn it under
        backoff until the crash-loop budget is spent."""
        id = str(id)
        if id in self.processes:
            raise ValueError(f"process id exists: {id}")
        stale = self._supervised.pop(id, None)
        if stale is not None and stale.pending_timer is not None:
            # a previous incarnation awaiting respawn: this spawn
            # supersedes it — its timer must not resurrect the old argv
            self.engine.remove_timer_handler(stale.pending_timer)
            stale.pending_timer = None
        if isinstance(command, str):
            argv = shlex.split(command) + [str(a) for a in arguments]
        else:
            argv = list(command) + [str(a) for a in arguments]
        pid = self._launch(id, argv, popen_kwargs)
        if restart is not None:    # only supervise a launch that succeeded
            self._supervised[id] = _Supervised(argv, popen_kwargs, restart)
        return pid

    def _launch(self, id: str, argv, popen_kwargs) -> int:
        process = subprocess.Popen(argv, **popen_kwargs)
        self.processes[id] = process
        self.logger.info("spawned %s: pid %s: %s", id, process.pid,
                         " ".join(argv))
        return process.pid

    def spawn_python(self, id, module: str, arguments=(), **popen_kwargs):
        """Launch `python -m module args...` with this interpreter."""
        return self.spawn(id, [sys.executable, "-m", module], arguments,
                          **popen_kwargs)

    def delete(self, id, kill: bool = True, timeout: float = 5.0) -> None:
        id = str(id)
        supervised = self._supervised.pop(id, None)
        if supervised is not None and supervised.pending_timer is not None:
            self.engine.remove_timer_handler(supervised.pending_timer)
            supervised.pending_timer = None
        process = self.processes.pop(id, None)
        if process is None:
            return
        if kill and process.poll() is None:
            process.terminate()
            try:
                process.wait(timeout)
            except subprocess.TimeoutExpired:
                process.kill()
                process.wait()

    def process_ids(self):
        return list(self.processes)

    def __contains__(self, id):
        return str(id) in self.processes

    def restart_state(self, id) -> dict:
        """Supervision diagnostics for an id: restart count inside the
        window, crash-loop flag, respawn pending."""
        supervised = self._supervised.get(str(id))
        if supervised is None:
            return {}
        return {"recent_exits": len(supervised.window.events),
                "crash_looping": supervised.crash_looping,
                "respawn_pending": supervised.pending_timer is not None}

    def _poll(self) -> None:
        for id, process in list(self.processes.items()):
            return_code = process.poll()
            if return_code is None:
                continue
            del self.processes[id]
            self.logger.info("process %s (pid %s) exited: %s", id,
                             process.pid, return_code)
            restarting = self._maybe_restart(id, return_code)
            if self.process_exit_handler and not restarting:
                try:
                    self.process_exit_handler(id, process.pid, return_code)
                except Exception:
                    self.logger.exception("exit handler raised for %s", id)

    def _maybe_restart(self, id: str, return_code) -> bool:
        """Schedule a supervised respawn; True when one is pending (the
        exit is then an internal event, not a terminal one)."""
        supervised = self._supervised.get(id)
        if supervised is None or supervised.crash_looping:
            return False
        policy = supervised.policy
        if return_code == 0 and not policy.restart_on_success:
            self._supervised.pop(id, None)      # clean exit: done
            return False
        delay = supervised.window.record(self.engine.clock.now())
        if delay is None:
            supervised.crash_looping = True
            self.logger.error(
                "process %s: crash loop (%d exits in %.1fs); giving up",
                id, len(supervised.window.events), policy.window)
            if self.crash_loop_handler:
                try:
                    self.crash_loop_handler(
                        id, list(supervised.window.events))
                except Exception:
                    self.logger.exception("crash-loop handler raised "
                                          "for %s", id)
            return False
        self.logger.warning("process %s exited %s; restart %d/%d in %.2fs",
                            id, return_code,
                            len(supervised.window.events),
                            policy.max_restarts, delay)
        supervised.pending_timer = self.engine.add_oneshot_handler(
            lambda: self._respawn(id), delay)
        return True

    def _respawn(self, id: str) -> None:
        supervised = self._supervised.get(id)
        if supervised is None:
            return
        supervised.pending_timer = None
        if id in self.processes:        # re-spawned by hand meanwhile
            return
        try:
            self._launch(id, supervised.argv, supervised.popen_kwargs)
        except Exception as exc:
            # a failed launch is an exit: re-enter the restart window so
            # the backoff/crash-loop budget governs it, and surface the
            # terminal failure instead of silently ending supervision
            self.logger.exception("respawn of %s failed", id)
            restarting = self._maybe_restart(id, f"spawn failed: {exc!r}")
            if self.process_exit_handler and not restarting:
                try:
                    self.process_exit_handler(id, None, exc)
                except Exception:
                    self.logger.exception("exit handler raised for %s", id)

    def terminate(self, kill_children: bool = True) -> None:
        self.engine.remove_timer_handler(self._timer)
        for id in list(self.processes):
            self.delete(id, kill=kill_children)
        for supervised in self._supervised.values():
            if supervised.pending_timer is not None:
                self.engine.remove_timer_handler(supervised.pending_timer)
                supervised.pending_timer = None
        self._supervised.clear()
