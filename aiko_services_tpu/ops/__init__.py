# Compute ops: device-side kernels and host-side scheduling for the
# inference data plane (SURVEY.md §7).  jax imports stay inside modules so
# the control plane never pays for them.

from .batching import (                                     # noqa: F401
    BatchItem, BatchingScheduler, ShapeBuckets,
)

__all__ = ["BatchItem", "BatchingScheduler", "ShapeBuckets"]
