# Compute ops: device-side kernels and host-side scheduling for the
# inference data plane (SURVEY.md §7).  jax imports stay inside modules so
# the control plane never pays for them.

from .admission import (                                    # noqa: F401
    AdmissionGate, TenantFairQueue, TenantPolicy,
)
from .batching import (                                     # noqa: F401
    BatchItem, BatchingScheduler, ShapeBuckets,
)

__all__ = ["AdmissionGate", "BatchItem", "BatchingScheduler",
           "ShapeBuckets", "TenantFairQueue", "TenantPolicy"]
