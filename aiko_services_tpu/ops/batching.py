# Continuous batching: many streams, one device, bounded latency.
#
# The reference processes one frame at a time per pipeline, sequentially
# (reference hot loop: aiko_services/pipeline.py:650-712) — its throughput
# ceiling is one stream per process.  The TPU replacement (SURVEY.md §7
# idiom 3): frames from many streams accumulate in per-bucket queues keyed
# by padded shape; the scheduler drains a full batch as soon as (a) the
# batch is full, or (b) the oldest frame has waited max_wait — bounding p50
# latency while keeping the MXU fed with large batches.  Shape bucketing
# bounds XLA recompilation: each (bucket_shape, batch_size) pair compiles
# once, ever.

from __future__ import annotations

import bisect
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from ..utils.lock import Lock

__all__ = ["BatchItem", "BatchingScheduler", "ShapeBuckets"]


class ShapeBuckets:
    """Monotone bucket ladder: a length is padded up to the next bucket so
    only len(buckets) shapes ever reach the compiler."""

    def __init__(self, buckets):
        self.buckets = sorted(buckets)

    def bucket_for(self, length: int) -> int:
        index = bisect.bisect_left(self.buckets, length)
        if index == len(self.buckets):
            raise ValueError(
                f"length {length} exceeds largest bucket "
                f"{self.buckets[-1]}")
        return self.buckets[index]


@dataclass
class BatchItem:
    stream_id: str
    payload: Any
    enqueue_time: float
    callback: Callable          # callback(stream_id, result)
    bucket: int = 0
    deadline: float | None = None   # absolute completion target


@dataclass
class _Bucket:
    items: deque = field(default_factory=deque)


class BatchingScheduler:
    """Arrival-driven batch former.

    process_batch(bucket, items) -> list[result] is called on the
    scheduler's drive thread (or the caller of drain() in inline mode)
    with at most max_batch items of one bucket; results fan back out
    through each item's callback.  Latency contract: an item waits at most
    max_wait before its (possibly partial) batch is dispatched.
    """

    def __init__(self, process_batch, buckets: ShapeBuckets,
                 max_batch: int = 32, max_wait: float = 0.05,
                 clock=time.monotonic, dispatch_gate=None,
                 metrics_labels: dict | None = None):
        self.process_batch = process_batch
        self.buckets = buckets
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.clock = clock
        # dispatch_gate() -> bool: when False, drain stops dispatching
        # (the pipelined path bounds how many batches are in flight on
        # the device at once — overlap depth is explicit and tunable,
        # not an accident of arrival timing)
        self.dispatch_gate = dispatch_gate
        self._lock = Lock("batching.scheduler")
        self._queues: dict[int, _Bucket] = {}
        # EWMA of recent per-batch service time (dispatch → results),
        # fed back by the owner via observe_service_time(): the
        # deadline-at-risk test needs to know how long a batch takes
        self._service_ewma: dict[int, float] = {}
        # cumulative counters, mirrored onto the process metrics
        # registry (batch_scheduler_total{kind=...}); metrics_labels
        # (e.g. {"program": name}) separates schedulers per series
        from ..observe.metrics import MirroredStats
        self.stats = MirroredStats(
            {"batches": 0, "items": 0, "batch_size_sum": 0,
             "full_batches": 0, "wait_sum": 0.0,
             "gated": 0, "deadline_dispatches": 0},
            metric="batch_scheduler_total",
            help="continuous-batching scheduler events by kind",
            labels=metrics_labels,
            # sums are levels, not events — dict-only (see serving.py)
            skip=("batch_size_sum", "wait_sum"))
        # rolling queue-wait samples (seconds) for percentile reporting
        self.recent_waits: deque = deque(maxlen=4096)

    def submit(self, stream_id: str, payload, length: int,
               callback, deadline: float | None = None) -> None:
        """Enqueue one item.  `deadline` (absolute, scheduler clock) is
        the item's completion target: the batch former dispatches a
        partial batch EARLY when waiting longer would make the earliest
        deadline unmeetable, instead of sitting out the full max_wait."""
        bucket = self.buckets.bucket_for(length)
        item = BatchItem(stream_id, payload, self.clock(), callback,
                         bucket, deadline)
        with self._lock:
            self._queues.setdefault(bucket, _Bucket()).items.append(item)

    def observe_service_time(self, bucket: int, seconds: float) -> None:
        """Feed back a measured batch service time (dispatch → results
        delivered) so deadline-at-risk admission has a current
        estimate.  EWMA, alpha=0.3."""
        with self._lock:
            prior = self._service_ewma.get(bucket)
            self._service_ewma[bucket] = seconds if prior is None \
                else 0.7 * prior + 0.3 * seconds

    def service_estimate(self, bucket: int) -> float | None:
        with self._lock:
            return self._service_ewma.get(bucket)

    def estimated_wait(self, bucket_key: int | None = None,
                       extra: int = 1) -> float | None:
        """Expected queue wait for the NEXT `extra` item(s) submitted to
        `bucket_key` (None = worst case over every non-empty bucket):
        batch-forming delay plus the service time of every batch ahead
        of — and including — the one the item would join.

            wait ≈ forming_delay + ceil((occupancy + extra) / max_batch)
                   × service_ewma

        forming_delay is the head item's remaining max_wait share (the
        joining batch won't dispatch before it fills or the head ages
        out); it collapses to 0 once the joining batch would be full.
        With no service EWMA yet (cold scheduler) the observed mean
        queue wait substitutes — the same level mirrored into the
        registry's batch_mean_wait_ms gauge — and a scheduler that has
        never dispatched returns None: the admission gate must not shed
        on a number it doesn't have."""
        now = self.clock()
        with self._lock:
            if bucket_key is None:
                keys = [k for k, b in self._queues.items() if b.items]
                if not keys:
                    keys = list(self._service_ewma)
                if not keys:
                    return self.mean_wait() if self.stats["items"] \
                        else None
                return max(
                    (w for w in (self._estimate_locked(k, extra, now)
                                 for k in keys) if w is not None),
                    default=None)
            return self._estimate_locked(bucket_key, extra, now)

    def _estimate_locked(self, bucket_key: int, extra: int,
                         now: float) -> float | None:
        bucket = self._queues.get(bucket_key)
        occupancy = len(bucket.items) if bucket is not None else 0
        estimate = self._service_ewma.get(bucket_key)
        if estimate is None:
            # cold bucket: the scheduler-wide mean wait is the only
            # signal there is (it feeds batch_mean_wait_ms)
            return self.mean_wait() if self.stats["items"] else None
        joining = occupancy + max(1, extra)
        if joining >= self.max_batch:
            forming = 0.0
        elif bucket is not None and bucket.items:
            head_age = now - bucket.items[0].enqueue_time
            forming = max(0.0, self.max_wait - head_age)
        else:
            forming = self.max_wait
        batches_ahead = -(-joining // self.max_batch)   # ceil division
        return forming + batches_ahead * estimate

    def _deadline_at_risk(self, bucket_key: int, bucket: _Bucket,
                          now: float) -> bool:
        """True when waiting any longer would likely miss the earliest
        deadline in this bucket: remaining slack has shrunk to the
        estimated service time."""
        estimate = self._service_ewma.get(bucket_key)
        if estimate is None:
            return False
        earliest = min((i.deadline for i in bucket.items
                        if i.deadline is not None), default=None)
        return earliest is not None and earliest - now <= estimate

    def _ready_bucket(self, now: float):
        """A bucket is ready when full, its head item is older than
        max_wait, or its earliest deadline is at risk.  Oldest head
        wins (FIFO fairness across buckets).  Returns
        (bucket_key, deadline_driven) or None — the caller counts
        deadline dispatches only when the batch actually dispatches
        (a gated drain may probe the same at-risk bucket repeatedly)."""
        best, best_age = None, -1.0
        for bucket_key, bucket in self._queues.items():
            if not bucket.items:
                continue
            age = now - bucket.items[0].enqueue_time
            if len(bucket.items) >= self.max_batch:
                age += 1e6          # full batch: dispatch first
            if age > best_age:
                best, best_age = bucket_key, age
        if best is None:
            return None
        bucket = self._queues[best]
        if len(bucket.items) >= self.max_batch or \
                best_age >= self.max_wait:
            return best, False
        # the at-risk test must cover EVERY bucket, not just the one
        # with the oldest head — a younger bucket can hold the tighter
        # deadline
        for bucket_key, bucket in self._queues.items():
            if bucket.items and self._deadline_at_risk(bucket_key,
                                                       bucket, now):
                return bucket_key, True
        return None

    def next_deadline(self) -> float | None:
        """When the next dispatch is due: now for an already-full bucket,
        else the sooner of (oldest item's max_wait expiry, the moment
        the earliest completion deadline becomes at-risk)."""
        with self._lock:
            dues = []
            for bucket_key, bucket in self._queues.items():
                if not bucket.items:
                    continue
                if len(bucket.items) >= self.max_batch:
                    return self.clock()        # dispatchable right now
                due = bucket.items[0].enqueue_time + self.max_wait
                estimate = self._service_ewma.get(bucket_key)
                if estimate is not None:
                    earliest = min((i.deadline for i in bucket.items
                                    if i.deadline is not None),
                                   default=None)
                    if earliest is not None:
                        due = min(due, earliest - estimate)
                dues.append(due)
        return min(dues) if dues else None

    def pending(self) -> int:
        with self._lock:
            return sum(len(b.items) for b in self._queues.values())

    def drain(self, force: bool = False) -> int:
        """Dispatch ready batches; force=True flushes everything.  Returns
        the number of items processed."""
        processed = 0
        while True:
            now = self.clock()
            with self._lock:
                ready = self._ready_bucket(now)
                deadline_driven = False
                if ready is not None:
                    bucket_key, deadline_driven = ready
                elif force:
                    nonempty = [k for k, b in self._queues.items()
                                if b.items]
                    bucket_key = nonempty[0] if nonempty else None
                else:
                    bucket_key = None
                if bucket_key is None:
                    return processed
                # force (teardown) bypasses the gate: every queued item
                # must reach its callback even over-depth
                if not force and self.dispatch_gate is not None and \
                        not self.dispatch_gate():
                    self.stats["gated"] += 1
                    return processed
                if deadline_driven:
                    self.stats["deadline_dispatches"] += 1
                queue = self._queues[bucket_key].items
                batch = [queue.popleft()
                         for _ in range(min(self.max_batch, len(queue)))]
            # items are already popped: every callback MUST fire, or the
            # stream's frame silently vanishes — errors fan out as results.
            # process_batch may return None: it took ownership of the
            # items and will fire their callbacks itself (the pipelined
            # results path: device work dispatched async, a worker thread
            # syncs + delivers, and the NEXT batch collates while this one
            # computes — host↔device transfer overlaps device compute).
            deferred = False
            try:
                results = self.process_batch(bucket_key, batch)
                if results is None:
                    deferred = True
                elif len(results) != len(batch):
                    raise RuntimeError(
                        f"process_batch returned {len(results)} results "
                        f"for {len(batch)} items")
            except Exception as exc:
                results = [exc] * len(batch)
            self.stats["batches"] += 1
            self.stats["items"] += len(batch)
            self.stats["batch_size_sum"] += len(batch)
            self.stats["full_batches"] += \
                int(len(batch) >= self.max_batch)
            waits = [now - i.enqueue_time for i in batch]
            self.stats["wait_sum"] += sum(waits)
            self.recent_waits.extend(waits)
            if not deferred:
                for item, result in zip(batch, results):
                    item.callback(item.stream_id, result)
            processed += len(batch)

    def attach(self, engine, period: float = 0.005) -> int:
        """Drive from an EventEngine: a fast timer checks deadlines and
        drains ready batches (control plane integration)."""
        return engine.add_timer_handler(lambda: self.drain(), period)

    def mean_batch_size(self) -> float:
        batches = self.stats["batches"]
        return self.stats["batch_size_sum"] / batches if batches else 0.0

    def mean_wait(self) -> float:
        items = self.stats["items"]
        return self.stats["wait_sum"] / items if items else 0.0
