# Fused attention: pallas flash-attention kernel for TPU, XLA fallback
# elsewhere.
#
# The hot op of every model in models/ (SURVEY.md §7 "hard parts": fused
# streaming attention).  Flash algorithm: tile Q into VMEM blocks, stream
# K/V blocks through, keep the online-softmax running max/normalizer in
# f32 scratch — the S×S score matrix never touches HBM, so the op is
# compute-bound on the MXU instead of bandwidth-bound.
#
# Block sizes honour the (8,128)/(16,128) tiling floors
# (/opt/skills/guides/pallas_guide.md "Tiling Constraints").

from __future__ import annotations

import functools
import math

__all__ = ["flash_attention", "attention", "cross_decode_attention"]


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch,
                  acc_scratch, *, causal: bool, scale: float,
                  block_q: int, block_k: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(2)            # grid: (batch*heads, 1, q_blocks)
    k_idx = pl.program_id(3)
    k_blocks = pl.num_programs(3)

    @pl.when(k_idx == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, -jnp.inf)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def compute():
        q = q_ref[0]                    # [block_q, d]
        k = k_ref[0]                    # [block_k, d]
        v = v_ref[0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1)
            scores = jnp.where(k_pos <= q_pos, scores, -jnp.inf)

        m_prev = m_scratch[:]                       # [bq, 1]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - m_safe)
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        correction = jnp.where(jnp.isneginf(m_prev), 0.0,
                               jnp.exp(m_prev - m_safe))
        m_scratch[:] = m_new
        l_scratch[:] = l_scratch[:] * correction + \
            jnp.sum(p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * correction + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    if causal:
        # skip fully-masked K blocks (block strictly above the diagonal)
        @pl.when(k_idx * block_k <= q_idx * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(k_idx == k_blocks - 1)
    def _finish():
        l = l_scratch[:]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def flash_attention(q, k, v, causal: bool = False,
                    scale: float | None = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused attention.  q,k,v: [B, H, S, D] → [B, H, S, D].

    interpret=None auto-selects: compiled pallas on TPU, interpreter mode
    elsewhere (CPU tests run the same kernel code path)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"sequence {s} not divisible by blocks "
                         f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)

    grid = (bh, 1, s // block_q, s // block_k)
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, _, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, _, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, _, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, _, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


# Measured crossover on the v5e bench chip (2026-07-29, b=32 h=12 d=64):
# at s=256 the flash kernel is ~15 ms SLOWER inside the whisper encoder
# than XLA's fused attention (full-model p50 154→173 ms), while at
# s=1536 flash wins 14.4 vs 21.2 ms/op — blockwise streaming only pays
# once the s×s score tensor is big enough that XLA must materialize it
# through HBM.  Dispatch accordingly.
FLASH_MIN_SEQ = 1024

# trace-time path counters: which implementation the dispatcher chose for
# each compiled program (bench --debug asserts on these)
dispatch_stats = {"flash": 0, "xla": 0}


def attention(q, k, v, causal: bool = False, scale: float | None = None):
    """Dispatch: pallas flash kernel on TPU for long sequences (where
    blockwise streaming beats materializing the score tensor), plain XLA
    attention otherwise — the measured winner at short sequences."""
    import jax

    s, d = q.shape[2], q.shape[3]
    if jax.default_backend() == "tpu" and s >= FLASH_MIN_SEQ \
            and s % 128 == 0 and d % 64 == 0:
        dispatch_stats["flash"] += 1
        return flash_attention(q, k, v, causal=causal, scale=scale)
    dispatch_stats["xla"] += 1
    from ..parallel.ring_attention import attention_reference
    return attention_reference(q, k, v, causal=causal, scale=scale)


def _cross_decode_kernel(q_ref, k_ref, v_ref, o_ref, *, heads: int,
                         t_real: int, scale: float):
    """One batch item per program: q [1, H, D] attends its full
    precomputed cross-K/V [1, H, T_pad, D].  T fits VMEM whole, so
    plain (not online) softmax; the win over XLA is streaming each
    K/V byte exactly once through a pipelined grid instead of 2×H
    tiny-M batched matmuls dominating the schedule."""
    import jax
    import jax.numpy as jnp

    for h in range(heads):                       # static unroll
        qh = q_ref[0, h:h + 1, :]                # [1, D]
        kh = k_ref[0, h]                         # [T_pad, D]
        vh = v_ref[0, h]
        scores = jax.lax.dot_general(
            qh, kh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [1, T_pad]
        t_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(t_pos < t_real, scores, -jnp.inf)
        # t_real >= 1, so m is finite and exp(-inf - m) is exactly 0
        # for the padded positions — no extra masking pass needed
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        out = jax.lax.dot(p.astype(vh.dtype), vh,
                          preferred_element_type=jnp.float32) / l
        o_ref[0, h:h + 1, :] = out.astype(o_ref.dtype)


def cross_decode_attention(q, k, v, scale: float | None = None,
                           interpret: bool | None = None):
    """Decode-time cross attention: q [B, H, 1, D], k/v [B, H, T, D]
    (precomputed, read-only) → [B, H, 1, D].

    RECORDED DEAD END (kept so later rounds don't retry it blind):
    the hypothesis was that XLA's 2×B×H M=1 matmuls are issue-bound
    (the whisper decode tail measures ~2.5× its bandwidth floor), and
    a grid-(B,) kernel with one item's K/V resident in VMEM would
    make the DMA the only cost.  Measured IN-PROGRAM on the v5e bench
    chip (2026-07-31, B=256 H=12 T=250 D=64, 24-token whisper tail):
    632 ms vs XLA's 243 ms — 2.6× SLOWER.  The per-program
    12-head unrolled small-matmul chain stalls the pipeline far worse
    than XLA's batched schedule; a winning kernel would need
    multi-item M-packing and is left for a future round.  The kernel
    is numerically correct (max abs err ~4e-3 bf16 vs reference) and
    tested in interpret mode."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, q_len, d = q.shape
    t = k.shape[2]
    if q_len != 1:
        raise ValueError(f"decode kernel needs q_len 1, got {q_len}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t_pad = -(-t // 128) * 128
    if t_pad != t:
        pad = ((0, 0), (0, 0), (0, t_pad - t), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    q2 = q[:, :, 0, :]                           # [B, H, D]
    kernel = functools.partial(_cross_decode_kernel, heads=h,
                               t_real=t, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, h, t_pad, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, t_pad, d), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(q2, k, v)
    return out[:, :, None, :]
