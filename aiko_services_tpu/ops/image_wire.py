# Camera-frame wire codec: 8x8 block DCT, quantized int8, top-K zigzag
# coefficients.
#
# The host->device wire is the scarce resource for camera pipelines (the
# reference ships frames to its CUDA models in-process and never meets
# this constraint; here a tunneled/PCIe hop carries every frame).  Raw
# uint8 RGB is already "compressed" per pixel, so the remaining lever is
# transform coding.  Real JPEG can't be decoded by XLA (entropy-coded
# bitstream), but a FIXED-LAYOUT transform codec can: the host runs a
# blockwise DCT + JPEG-style quantization and ships the first K zigzag
# coefficients as int8; the device dequantizes and inverts the DCT with
# two 8x8 matmuls — static shapes, fully fusible into the consumer
# program (PE_Detect fuses decode+normalize+model into one XLA program,
# the same pattern as the ASR element's mu-law wire).
#
# keep=16 -> 4x fewer wire bytes than raw uint8; keep=10 -> 6.4x.

from __future__ import annotations

import numpy as np

__all__ = ["dct8_encode", "dct8_decode", "dct8_wire_bytes", "DCT_KEEP"]

DCT_KEEP = 16                    # default coefficients kept per block


def _dct_basis() -> np.ndarray:
    """Orthonormal 8x8 DCT-II basis: Y = D @ X @ D.T."""
    k = np.arange(8)[:, None]
    n = np.arange(8)[None, :]
    basis = np.cos((2 * n + 1) * k * np.pi / 16.0)
    basis[0] *= np.sqrt(1.0 / 2.0)
    return (basis * np.sqrt(2.0 / 8.0)).astype(np.float32)


_DCT = _dct_basis()

# JPEG Annex K luminance quantization (quality ~50); shared across
# channels — chroma fidelity matters less for detection than luma
_QUANT = np.array([
    [16, 11, 10, 16, 24, 40, 51, 61],
    [12, 12, 14, 19, 26, 58, 60, 55],
    [14, 13, 16, 24, 40, 57, 69, 56],
    [14, 17, 22, 29, 51, 87, 80, 62],
    [18, 22, 37, 56, 68, 109, 103, 77],
    [24, 35, 55, 64, 81, 104, 113, 92],
    [49, 64, 78, 87, 103, 121, 120, 101],
    [72, 92, 95, 98, 112, 100, 103, 99]], np.float32)


def _zigzag_order() -> np.ndarray:
    """Indices of the 64 block positions in zigzag scan order."""
    order = sorted(((i, j) for i in range(8) for j in range(8)),
                   key=lambda p: (p[0] + p[1],
                                  p[1] if (p[0] + p[1]) % 2 else p[0]))
    return np.array([i * 8 + j for i, j in order], np.int32)


_ZIGZAG = _zigzag_order()


def dct8_wire_bytes(height: int, width: int, channels: int = 3,
                    keep: int = DCT_KEEP) -> int:
    return (height // 8) * (width // 8) * channels * keep


def dct8_encode(image: np.ndarray, keep: int = DCT_KEEP) -> np.ndarray:
    """uint8 [H, W, C] (H, W multiples of 8) -> int8
    [H/8, W/8, C, keep] quantized zigzag DCT coefficients."""
    h, w, c = image.shape
    if h % 8 or w % 8:
        raise ValueError(f"dct8 needs 8-aligned frames, got {h}x{w}")
    x = image.astype(np.float32) - 128.0
    blocks = x.reshape(h // 8, 8, w // 8, 8, c).transpose(0, 2, 4, 1, 3)
    coeffs = np.einsum("ki,bwcij,lj->bwckl", _DCT, blocks, _DCT,
                       optimize=True)
    quantized = np.round(coeffs / _QUANT).reshape(
        h // 8, w // 8, c, 64)[..., _ZIGZAG[:keep]]
    return np.clip(quantized, -127, 127).astype(np.int8)


def dct8_decode(codes, height: int, width: int):
    """int8 [B, H/8, W/8, C, keep] -> float32 [B, H, W, C] in [0, 1].

    jax/XLA path — built from matmuls and a static scatter so the
    consumer program fuses it; runs under jit on TPU."""
    import jax.numpy as jnp

    batch, hb, wb, channels, keep = codes.shape
    flat = jnp.zeros((batch, hb, wb, channels, 64), jnp.float32)
    flat = flat.at[..., _ZIGZAG[:keep]].set(
        codes.astype(jnp.float32))
    coeffs = flat.reshape(batch, hb, wb, channels, 8, 8) * _QUANT
    dct = jnp.asarray(_DCT)
    blocks = jnp.einsum("ik,bwhckl,jl->bwhcij", dct.T, coeffs, dct.T)
    image = (blocks + 128.0).transpose(0, 1, 4, 2, 5, 3).reshape(
        batch, height, width, channels)
    return jnp.clip(image, 0.0, 255.0) / 255.0
