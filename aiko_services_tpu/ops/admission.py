# Overload control for serving runtimes: deadline-aware admission and
# per-tenant weighted fair queuing (ISSUE 9, ROADMAP item 2).
#
# The primitives this composes all exist — frame deadlines ride the wire
# (observe/tracing.py), the batch former estimates its own queue wait
# (ops/batching.py estimated_wait), and every decision mirrors into the
# process metrics registry — but before this module an overloaded
# serving runtime simply queued until deadlines blew.  The SEDA /
# Breakwater discipline instead:
#
#   * shed EARLY, at the cheapest point: a request whose remaining
#     deadline budget cannot survive the estimated queue wait is
#     answered with a failure reply IMMEDIATELY (one dedup-cached
#     control message), so the caller fails over to another candidate
#     instead of burning broker round-trips on doomed work;
#   * isolate tenants: a weighted deficit-round-robin queue in front of
#     the walk gives each tenant a budget per priority tier; overload
#     sheds newest-first WITHIN the over-budget tenant only, so a
#     flooding tenant cannot push a polite tenant past its SLO;
#   * make every verdict observable: admission_{admitted,shed,rejected}
#     _total{tenant,tier,reason} counters and per-tenant queue-depth
#     gauges, the numbers the autoscaler and the soak assert on.
#
# The module is transport-free: the Pipeline serving entry
# (pipeline.process_frame_remote) and bench harnesses plug in their own
# dispatch/shed callables.

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable

from ..observe.metrics import MetricsRegistry, default_registry

__all__ = ["TenantPolicy", "TenantFairQueue", "AdmissionGate",
           "DeadlineRouter", "DEFAULT_TENANT"]

DEFAULT_TENANT = "default"


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant fair-queuing knobs.

    weight:       DRR quantum share within the tenant's tier (2.0
                  drains twice as fast as 1.0 under contention);
    tier:         strict priority band — tier 0 drains before tier 1
                  has any items dispatched, and so on;
    queue_budget: max frames this tenant may have queued (None → the
                  queue's base_budget × weight)."""
    weight: float = 1.0
    tier: int = 1
    queue_budget: int | None = None


@dataclass
class _TenantState:
    name: str
    policy: TenantPolicy
    items: deque            # (item, shed_callable, cost, enqueued_t)
    deficit: float = 0.0
    depth_gauge: object = None


class TenantFairQueue:
    """Weighted deficit-round-robin admission queue.

    submit() enqueues one item under its tenant (shedding when the
    tenant is over budget); drain(dispatch) releases items in strict
    tier order, DRR-weighted within a tier, calling dispatch(item) for
    each.  Items carry a shed callable so a dropped frame can still
    answer its caller (the serving dedup ring depends on every hop
    getting a reply)."""

    def __init__(self, policies: dict | None = None,
                 default_policy: TenantPolicy | None = None,
                 base_budget: int = 32,
                 global_budget: int | None = None,
                 quantum: float = 1.0,
                 registry: MetricsRegistry | None = None,
                 metrics_labels: dict | None = None,
                 clock: Callable | None = None):
        self._policies = dict(policies or {})
        self._default_policy = default_policy or TenantPolicy()
        self.base_budget = max(1, int(base_budget))
        # global cap across tenants: breach sheds from the MOST
        # over-budget tenant (queued ÷ weight), never from a polite one
        self.global_budget = int(global_budget) if global_budget else None
        self.quantum = float(quantum)
        self._tenants: dict[str, _TenantState] = {}
        self._registry = registry or default_registry()
        self._labels = dict(metrics_labels or {})
        self._counter_cache: dict = {}
        # MEASURED per-tenant queue dwell (ISSUE 12): with a clock
        # (callable → seconds; the pipeline passes the engine clock so
        # virtual-clock tests stay deterministic) every drained item
        # observes (dispatch - enqueue) into
        # admission_queue_wait_seconds{tenant} — the number the
        # request journey records, where the gate's estimated_wait is
        # only a forecast.  last_dispatch_wait exposes the most recent
        # measurement to the dispatch callback (drain calls dispatch
        # synchronously right after observing), so callers record ONE
        # dwell, not a parallel re-measurement.
        self._clock = clock
        self._wait_histograms: dict = {}
        self.last_dispatch_wait: float | None = None

    def set_clock(self, clock: Callable) -> None:
        """Install a dwell clock unless the builder already chose one
        — how the Pipeline hands its engine clock to an externally
        constructed gate."""
        if self._clock is None:
            self._clock = clock

    # -- metrics -----------------------------------------------------------
    def _count(self, family: str, tenant: str, tier: int,
               reason: str) -> None:
        key = (family, tenant, tier, reason)
        counter = self._counter_cache.get(key)
        if counter is None:
            counter = self._registry.counter(
                f"admission_{family}_total",
                f"admission verdicts: frames {family}",
                labels={**self._labels, "tenant": tenant,
                        "tier": str(tier), "reason": reason})
            self._counter_cache[key] = counter
        counter.inc()

    def _state(self, tenant: str, tier: int | None) -> _TenantState:
        state = self._tenants.get(tenant)
        if state is None:
            policy = self._policies.get(tenant, self._default_policy)
            if tier is not None and tenant not in self._policies:
                # caller-declared tier honoured only for tenants the
                # serving side has no explicit policy for
                policy = TenantPolicy(policy.weight, int(tier),
                                      policy.queue_budget)
            state = _TenantState(tenant, policy, deque())
            state.depth_gauge = self._registry.gauge(
                "admission_queue_depth",
                "frames queued per tenant awaiting admission",
                labels={**self._labels, "tenant": tenant,
                        "tier": str(policy.tier)})
            self._tenants[tenant] = state
        return state

    def _budget(self, state: _TenantState) -> int:
        if state.policy.queue_budget is not None:
            return max(1, int(state.policy.queue_budget))
        return max(1, int(self.base_budget * state.policy.weight))

    # -- enqueue / shed ----------------------------------------------------
    def submit(self, tenant: str, item, shed: Callable | None = None,
               tier: int | None = None, cost: float = 1.0) -> bool:
        """Queue one item; returns False when it was shed instead.
        Shedding is newest-first within the offending tenant only: the
        incoming frame IS the newest, so an over-budget tenant loses it
        (and, on a global-budget breach, the most over-budget tenant
        loses its own newest queued frame)."""
        tenant = str(tenant or DEFAULT_TENANT)
        state = self._state(tenant, tier)
        if len(state.items) >= self._budget(state):
            self._count("shed", tenant, state.policy.tier,
                        "tenant-over-budget")
            if shed is not None:
                shed(item)
            return False
        state.items.append((item, shed, float(cost),
                            self._clock() if self._clock is not None
                            else None))
        state.depth_gauge.set(len(state.items))
        if self.global_budget is not None and \
                self.depth() > self.global_budget:
            return self._shed_most_over_budget() is not item
        return True

    def _shed_most_over_budget(self):
        """Shed (and return) the newest queued item of the tenant most
        over its weighted share; None when nothing is queued."""
        worst, worst_ratio = None, -1.0
        for tenant, state in self._tenants.items():
            if not state.items:
                continue
            ratio = len(state.items) / max(state.policy.weight, 1e-9)
            if ratio > worst_ratio:
                worst, worst_ratio = tenant, ratio
        if worst is None:
            return None
        state = self._tenants[worst]
        item, shed, _, _ = state.items.pop()       # newest-first
        state.depth_gauge.set(len(state.items))
        self._count("shed", worst, state.policy.tier,
                    "global-over-budget")
        if shed is not None:
            shed(item)
        return item

    # -- drain -------------------------------------------------------------
    def drain(self, dispatch: Callable, limit: int | None = None) -> int:
        """Release up to `limit` items (None = everything eligible):
        strict tier priority, weighted DRR within each tier.  Returns
        the number dispatched."""
        released = 0
        tiers = sorted({s.policy.tier for s in self._tenants.values()
                        if s.items})
        for tier in tiers:
            while limit is None or released < limit:
                states = [s for s in self._tenants.values()
                          if s.items and s.policy.tier == tier]
                if not states:
                    break
                progressed = False
                for state in states:
                    if limit is not None and released >= limit:
                        break
                    state.deficit += self.quantum * state.policy.weight
                    while state.items and \
                            state.deficit >= state.items[0][2] and \
                            (limit is None or released < limit):
                        item, _, cost, enqueued_t = \
                            state.items.popleft()
                        state.deficit -= cost
                        state.depth_gauge.set(len(state.items))
                        self._count("admitted", state.name,
                                    state.policy.tier, "queued")
                        if enqueued_t is not None:
                            self._observe_wait(state.name,
                                               enqueued_t)
                        else:
                            self.last_dispatch_wait = None
                        dispatch(item)
                        released += 1
                        progressed = True
                    if not state.items:
                        state.deficit = 0.0     # DRR: idle tenants
                                                # bank no credit
                if not progressed:
                    break
        return released

    def _observe_wait(self, tenant: str, enqueued_t: float) -> None:
        histogram = self._wait_histograms.get(tenant)
        if histogram is None:
            histogram = self._registry.histogram(
                "admission_queue_wait_seconds",
                "measured fair-queue dwell per drained frame",
                labels={**self._labels, "tenant": tenant})
            self._wait_histograms[tenant] = histogram
        wait = max(0.0, self._clock() - enqueued_t)
        self.last_dispatch_wait = wait
        histogram.observe(wait)

    def depth(self, tenant: str | None = None) -> int:
        if tenant is not None:
            state = self._tenants.get(str(tenant))
            return len(state.items) if state else 0
        return sum(len(s.items) for s in self._tenants.values())

    def shed_all(self, reason: str = "shutdown") -> int:
        """Drop every queued item through its shed callable (newest
        first) — teardown must answer queued callers, not orphan them."""
        count = 0
        for state in self._tenants.values():
            while state.items:
                item, shed, _, _ = state.items.pop()
                self._count("shed", state.name, state.policy.tier,
                            reason)
                if shed is not None:
                    shed(item)
                count += 1
            state.depth_gauge.set(0)
            state.deficit = 0.0
        return count


class DeadlineRouter:
    """Deadline-aware routing across role-tagged serving candidates
    (ISSUE 14, the disaggregated prefill/decode split).

    A prompt whose remaining deadline budget is SHORT goes to the
    LEAST-LOADED candidate — time-to-first-token is its binding
    constraint, and queueing behind a loaded prefill runtime is
    exactly the wait shed-early would later punish.  Prompts with
    ample (or no) budget round-robin so the pool shares work evenly
    and the load signal stays meaningful.

    Transport-free like the gate: callers hand in a {candidate: load}
    snapshot (e.g. a PrefillClient's per-runtime outstanding-transfer
    counts, or pipeline placeholder candidates filtered by role) and
    the remaining budget in seconds.  Verdicts mirror into
    admission_routes_total{router, verdict}."""

    def __init__(self, urgent_budget_s: float = 1.0,
                 name: str = "router",
                 registry: MetricsRegistry | None = None,
                 on_route=None):
        self.urgent_budget_s = float(urgent_budget_s)
        self.name = str(name)
        self._rr = 0
        self._registry = registry or default_registry()
        self._counters: dict = {}
        # on_route(candidate, remaining) fires on every successful
        # verdict — the next-hop seam (ISSUE 17): a tiered KV cache
        # hangs its promotion prefetch here so host-resident chains
        # start re-landing the moment a destination is KNOWN, not when
        # the routed work finally lands.  Failures are swallowed: a
        # prefetch hook must never turn a route into an exception.
        self.on_route = on_route

    def _count(self, verdict: str) -> None:
        counter = self._counters.get(verdict)
        if counter is None:
            counter = self._registry.counter(
                "admission_routes_total",
                "deadline-router verdicts by kind",
                labels={"router": self.name, "verdict": verdict})
            self._counters[verdict] = counter
        counter.inc()

    def route(self, loads: dict, remaining: float | None) -> str | None:
        """Pick one candidate from {candidate: load}; None when the
        pool is empty (the caller's fallback ladder takes over)."""
        if not loads:
            self._count("no-candidates")
            return None
        order = sorted(loads)           # deterministic tie-break
        if remaining is not None and remaining <= self.urgent_budget_s:
            self._count("urgent-least-loaded")
            choice = min(order,
                         key=lambda c: (float(loads[c] or 0.0), c))
        else:
            self._count("round-robin")
            choice = order[self._rr % len(order)]
            self._rr += 1
        if self.on_route is not None:
            try:
                self.on_route(choice, remaining)
            except Exception:
                pass
        return choice


class AdmissionGate:
    """Deadline-aware admission in front of a serving pipeline.

    Two verdicts, in order:

      1. shed-early — estimated_wait() (max over the registered wait
         estimators, e.g. BatchingScheduler.estimated_wait, falling
         back to the registry's batch_mean_wait_ms gauge) plus `margin`
         exceeds the request's remaining deadline budget → reject NOW
         with a failure reply, before any queueing;
      2. fair queue — admitted requests enter the per-tenant DRR queue
         and drain while fewer than `inflight_limit` admitted frames
         are outstanding (credits returned via release() when the
         serving reply goes out).

    The gate owns no transport and no clock: callers hand in remaining
    budget (seconds) and completion callbacks."""

    def __init__(self, queue: TenantFairQueue | None = None,
                 margin: float = 0.0, inflight_limit: int = 32,
                 registry: MetricsRegistry | None = None,
                 metrics_labels: dict | None = None):
        self._registry = registry or default_registry()
        self._labels = dict(metrics_labels or {})
        self.queue = queue if queue is not None else TenantFairQueue(
            registry=self._registry, metrics_labels=metrics_labels)
        self.margin = float(margin)
        self.inflight_limit = max(1, int(inflight_limit))
        self.inflight = 0
        self._estimators: list[Callable] = []
        self._inflight_gauge = self._registry.gauge(
            "admission_inflight",
            "admitted frames awaiting their serving reply",
            labels=self._labels)

    # -- wait estimation ---------------------------------------------------
    def add_wait_estimator(self, estimator: Callable) -> None:
        """estimator() -> seconds | None; the gate uses the worst
        (largest) live estimate."""
        self._estimators.append(estimator)

    def watch_scheduler(self, scheduler) -> None:
        """Convenience: estimate from a BatchingScheduler's EWMA +
        occupancy (ops/batching.py estimated_wait)."""
        self.add_wait_estimator(scheduler.estimated_wait)

    def watch_decoder(self, decoder) -> None:
        """Convenience: estimate from a ContinuousDecoder's admit-wait
        heuristic (serving.estimated_admit_wait — round EWMA × backlog
        share).  With a prefix cache bound the decoder's estimate
        credits expected prefix hits when probed with a prompt, so the
        serving side sheds on the CACHED cost of a conversation turn,
        not its cold re-prefill cost (ISSUE 13); the gate's argless
        call sees the backlog component."""
        self.add_wait_estimator(decoder.estimated_admit_wait)

    def estimated_wait(self) -> float | None:
        waits = []
        for estimator in self._estimators:
            try:
                wait = estimator()
            except Exception:
                continue
            if wait is not None:
                waits.append(float(wait))
        if waits:
            return max(waits)
        # fallback: the batch former's mean queue wait, as mirrored
        # into the registry (batch_mean_wait_ms gauge, any program)
        gauges = [m.value for _, m in
                  self._registry.series("batch_mean_wait_ms")]
        if gauges:
            return max(gauges) / 1000.0
        return None

    # -- verdicts ----------------------------------------------------------
    def shed_early(self, remaining: float | None):
        """(shed?, estimated_wait): True when the remaining deadline
        budget cannot survive the estimated queue wait.  A request with
        no deadline, or a gate with no wait signal, never sheds here —
        admission must not drop work on information it doesn't have."""
        wait = self.estimated_wait()
        if remaining is None or wait is None:
            return False, wait
        return (wait + self.margin) >= remaining, wait

    def count_rejected(self, tenant: str, tier: int, reason: str) -> None:
        """Mirror a rejection verdict the caller enforced (shed-early,
        already-expired) into the admission counter family."""
        self.queue._count("rejected", str(tenant or DEFAULT_TENANT),
                          int(tier), reason)

    # -- byte-budget verdict (ISSUE 20) ------------------------------------
    def set_byte_policy(self, ledger, budget_bytes: int | None = None,
                        tenant_budgets: dict | None = None,
                        default_estimate: int = 0) -> None:
        """Arm capacity-aware shedding against a KV memory ledger.

        `budget_bytes` caps any single tenant's attributed device bytes
        (tenant_budgets overrides per tenant).  A request whose
        projected footprint — the tenant's live ledger balance plus the
        caller's per-request byte estimate — breaches its budget is
        shed UNLESS the pool-wide occupancy trend is already relieving
        fast enough to clear the overage inside the request's remaining
        deadline budget.  Disarmed (ledger or every budget None) the
        verdict never sheds."""
        self._byte_ledger = ledger
        self._byte_budget = None if budget_bytes is None else int(budget_bytes)
        self._tenant_budgets = dict(tenant_budgets or {})
        self._byte_estimate = max(0, int(default_estimate))

    def shed_on_bytes(self, tenant: str, estimate_bytes: int | None = None,
                      remaining: float | None = None):
        """(shed?, projected_bytes): True when admitting `tenant`'s next
        request would breach its byte budget with no relief in sight.
        Pairs with shed_early — callers reject with reason
        "byte-budget" via count_rejected when this verdict fires."""
        ledger = getattr(self, "_byte_ledger", None)
        if ledger is None:
            return False, None
        key = str(tenant or DEFAULT_TENANT)
        budget = self._tenant_budgets.get(key, self._byte_budget)
        if budget is None:
            return False, None
        estimate = self._byte_estimate if estimate_bytes is None \
            else max(0, int(estimate_bytes))
        projected = int(ledger.device_bytes(key)) + estimate
        if projected <= budget:
            return False, projected
        trend = ledger.device_trend()
        if trend is not None and trend < 0 and remaining is not None:
            relief = (projected - budget) / -trend
            if relief < remaining:
                return False, projected
        return True, projected

    # -- fair-queue passage ------------------------------------------------
    def offer(self, tenant: str, item, shed: Callable | None = None,
              tier: int | None = None,
              dispatch: Callable | None = None) -> bool:
        """Queue one admitted request and drain what the inflight
        window allows.  Returns False when the fair queue shed it."""
        queued = self.queue.submit(tenant, item, shed=shed, tier=tier)
        if queued and dispatch is not None:
            self.drain(dispatch)
        return queued

    def drain(self, dispatch: Callable) -> int:
        budget = self.inflight_limit - self.inflight
        if budget <= 0:
            return 0

        def run(item):
            self.inflight += 1
            self._inflight_gauge.set(self.inflight)
            dispatch(item)

        return self.queue.drain(run, limit=budget)

    def release(self, count: int = 1) -> None:
        """An admitted frame completed (its reply went out): return its
        inflight credit.  The owner should drain() afterwards."""
        self.inflight = max(0, self.inflight - count)
        self._inflight_gauge.set(self.inflight)
