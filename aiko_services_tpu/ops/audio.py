# Audio DSP ops: log-mel spectrogram frontend, on-device.
#
# Replaces the host-side librosa/torch feature extraction the reference's
# ASR element delegates to faster-whisper (reference: examples/speech/
# speech_elements.py:217-250).  Computing the mel frontend in jax keeps the
# microphone→features→encoder path on-device: one jit, no host round-trip
# between framing and the encoder (SURVEY.md §7 "host↔device I/O overlap").

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["mel_filterbank", "log_mel_spectrogram", "stft",
           "WHISPER_SAMPLE_RATE", "WHISPER_N_FFT", "WHISPER_HOP"]

WHISPER_SAMPLE_RATE = 16000
WHISPER_N_FFT = 400
WHISPER_HOP = 160


# Slaney mel scale (librosa default, what Whisper's frontend uses):
# linear below 1 kHz, logarithmic above.  NOT the HTK 2595*log10 form —
# they diverge above ~1 kHz and pretrained weights are scale-sensitive.
_MIN_LOG_HZ = 1000.0
_LIN_SLOPE = 3.0 / 200.0                      # mels per Hz below 1 kHz
_MIN_LOG_MEL = _MIN_LOG_HZ * _LIN_SLOPE       # 15.0
_LOG_STEP = math.log(6.4) / 27.0


def _hz_to_mel(hz: float) -> float:
    if hz < _MIN_LOG_HZ:
        return hz * _LIN_SLOPE
    return _MIN_LOG_MEL + math.log(hz / _MIN_LOG_HZ) / _LOG_STEP


def _mel_to_hz(mels):
    linear = mels / _LIN_SLOPE
    log = _MIN_LOG_HZ * np.exp(_LOG_STEP * (mels - _MIN_LOG_MEL))
    return np.where(mels < _MIN_LOG_MEL, linear, log)


@functools.lru_cache(maxsize=8)
def mel_filterbank(num_mels: int = 80, n_fft: int = WHISPER_N_FFT,
                   sample_rate: int = WHISPER_SAMPLE_RATE,
                   fmin: float = 0.0, fmax: float | None = None):
    """Slaney-scale triangular mel filterbank: [n_fft//2+1, num_mels].

    Computed in numpy: it is a compile-time constant, and the lru_cache
    must hold concrete arrays — building it with jnp under an enclosing
    jit would cache a tracer (leak) on first traced use."""
    fmax = fmax if fmax is not None else sample_rate / 2.0
    num_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0.0, sample_rate / 2.0, num_bins)
    mel_points = np.linspace(_hz_to_mel(fmin), _hz_to_mel(fmax),
                             num_mels + 2)
    hz_points = _mel_to_hz(mel_points)

    lower = hz_points[:-2][None, :]
    centre = hz_points[1:-1][None, :]
    upper = hz_points[2:][None, :]
    freqs = fft_freqs[:, None]
    up_slope = (freqs - lower) / np.maximum(centre - lower, 1e-10)
    down_slope = (upper - freqs) / np.maximum(upper - centre, 1e-10)
    weights = np.maximum(0.0, np.minimum(up_slope, down_slope))
    # Slaney area normalization
    enorm = 2.0 / (hz_points[2:] - hz_points[:-2])
    return (weights * enorm[None, :]).astype(np.float32)


def stft(audio, n_fft: int = WHISPER_N_FFT, hop: int = WHISPER_HOP):
    """audio: [B, T_samples] → magnitude² [B, T_frames, n_fft//2+1].
    Hann window, centred (reflect padding), matching whisper's frontend."""
    pad = n_fft // 2
    audio = jnp.pad(audio, ((0, 0), (pad, pad)), mode="reflect")
    num_frames = 1 + (audio.shape[1] - n_fft) // hop
    # frame extraction as a strided gather → [B, frames, n_fft]
    idx = (jnp.arange(num_frames)[:, None] * hop +
           jnp.arange(n_fft)[None, :])
    frames = audio[:, idx]
    window = jnp.hanning(n_fft + 1)[:-1].astype(audio.dtype)
    spectrum = jnp.fft.rfft(frames * window, axis=-1)
    return jnp.abs(spectrum) ** 2


def log_mel_spectrogram(audio, num_mels: int = 80,
                        n_fft: int = WHISPER_N_FFT,
                        hop: int = WHISPER_HOP,
                        sample_rate: int = WHISPER_SAMPLE_RATE):
    """audio: [B, T_samples] float in [-1, 1] → log-mel [B, T_frames, mels]
    (whisper normalization: log10, clamp to max-8, scale to ~[-1, 1])."""
    power = stft(audio.astype(jnp.float32), n_fft, hop)
    power = power[:, :-1]         # whisper drops the final frame
    mels = power @ mel_filterbank(num_mels, n_fft, sample_rate)
    log_spec = jnp.log10(jnp.maximum(mels, 1e-10))
    log_spec = jnp.maximum(log_spec,
                           jnp.max(log_spec, axis=(1, 2),
                                   keepdims=True) - 8.0)
    return (log_spec + 4.0) / 4.0
