# Audio DSP ops: log-mel spectrogram frontend, on-device.
#
# Replaces the host-side librosa/torch feature extraction the reference's
# ASR element delegates to faster-whisper (reference: examples/speech/
# speech_elements.py:217-250).  Computing the mel frontend in jax keeps the
# microphone→features→encoder path on-device: one jit, no host round-trip
# between framing and the encoder (SURVEY.md §7 "host↔device I/O overlap").

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["mel_filterbank", "log_mel_spectrogram", "stft",
           "stft_complex", "istft", "mel_to_linear", "mel_inverse_filterbank",
           "griffin_lim", "mulaw_encode", "mulaw_decode",
           "mel_cepstral_distortion",
           "WHISPER_SAMPLE_RATE", "WHISPER_N_FFT", "WHISPER_HOP"]

WHISPER_SAMPLE_RATE = 16000
WHISPER_N_FFT = 400
WHISPER_HOP = 160


# Slaney mel scale (librosa default, what Whisper's frontend uses):
# linear below 1 kHz, logarithmic above.  NOT the HTK 2595*log10 form —
# they diverge above ~1 kHz and pretrained weights are scale-sensitive.
_MIN_LOG_HZ = 1000.0
_LIN_SLOPE = 3.0 / 200.0                      # mels per Hz below 1 kHz
_MIN_LOG_MEL = _MIN_LOG_HZ * _LIN_SLOPE       # 15.0
_LOG_STEP = math.log(6.4) / 27.0


def _hz_to_mel(hz: float) -> float:
    if hz < _MIN_LOG_HZ:
        return hz * _LIN_SLOPE
    return _MIN_LOG_MEL + math.log(hz / _MIN_LOG_HZ) / _LOG_STEP


def _mel_to_hz(mels):
    linear = mels / _LIN_SLOPE
    log = _MIN_LOG_HZ * np.exp(_LOG_STEP * (mels - _MIN_LOG_MEL))
    return np.where(mels < _MIN_LOG_MEL, linear, log)


@functools.lru_cache(maxsize=8)
def mel_filterbank(num_mels: int = 80, n_fft: int = WHISPER_N_FFT,
                   sample_rate: int = WHISPER_SAMPLE_RATE,
                   fmin: float = 0.0, fmax: float | None = None):
    """Slaney-scale triangular mel filterbank: [n_fft//2+1, num_mels].

    Computed in numpy: it is a compile-time constant, and the lru_cache
    must hold concrete arrays — building it with jnp under an enclosing
    jit would cache a tracer (leak) on first traced use."""
    fmax = fmax if fmax is not None else sample_rate / 2.0
    num_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0.0, sample_rate / 2.0, num_bins)
    mel_points = np.linspace(_hz_to_mel(fmin), _hz_to_mel(fmax),
                             num_mels + 2)
    hz_points = _mel_to_hz(mel_points)

    lower = hz_points[:-2][None, :]
    centre = hz_points[1:-1][None, :]
    upper = hz_points[2:][None, :]
    freqs = fft_freqs[:, None]
    up_slope = (freqs - lower) / np.maximum(centre - lower, 1e-10)
    down_slope = (upper - freqs) / np.maximum(upper - centre, 1e-10)
    weights = np.maximum(0.0, np.minimum(up_slope, down_slope))
    # Slaney area normalization
    enorm = 2.0 / (hz_points[2:] - hz_points[:-2])
    return (weights * enorm[None, :]).astype(np.float32)


def stft(audio, n_fft: int = WHISPER_N_FFT, hop: int = WHISPER_HOP):
    """audio: [B, T_samples] → magnitude² [B, T_frames, n_fft//2+1].
    Hann window, centred (reflect padding), matching whisper's frontend."""
    pad = n_fft // 2
    audio = jnp.pad(audio, ((0, 0), (pad, pad)), mode="reflect")
    num_frames = 1 + (audio.shape[1] - n_fft) // hop
    # frame extraction as a strided gather → [B, frames, n_fft]
    idx = (jnp.arange(num_frames)[:, None] * hop +
           jnp.arange(n_fft)[None, :])
    frames = audio[:, idx]
    window = jnp.hanning(n_fft + 1)[:-1].astype(audio.dtype)
    spectrum = jnp.fft.rfft(frames * window, axis=-1)
    return jnp.abs(spectrum) ** 2


def log_mel_spectrogram(audio, num_mels: int = 80,
                        n_fft: int = WHISPER_N_FFT,
                        hop: int = WHISPER_HOP,
                        sample_rate: int = WHISPER_SAMPLE_RATE):
    """audio: [B, T_samples] float in [-1, 1] → log-mel [B, T_frames, mels]
    (whisper normalization: log10, clamp to max-8, scale to ~[-1, 1])."""
    power = stft(audio.astype(jnp.float32), n_fft, hop)
    power = power[:, :-1]         # whisper drops the final frame
    mels = power @ mel_filterbank(num_mels, n_fft, sample_rate)
    log_spec = jnp.log10(jnp.maximum(mels, 1e-10))
    log_spec = jnp.maximum(log_spec,
                           jnp.max(log_spec, axis=(1, 2),
                                   keepdims=True) - 8.0)
    return (log_spec + 4.0) / 4.0


def mel_cepstral_distortion(mel_a, mel_b, n_coeffs: int = 13) -> float:
    """MCD (dB) between two log_mel_spectrogram outputs [T, n_mels].

    The standard objective TTS quality metric: frames are converted to
    mel-cepstra (orthonormal DCT over the mel axis, c0 excluded — it
    only carries loudness), distances averaged over the overlapping
    frames.  Computed on the whisper-normalized log-mel scale, undone
    back to log10 first so the dB constant is meaningful; sequences of
    different length are truncated to the shorter one (synthetic
    corpus: timing is supervised, so misalignment stays sub-frame).
    Non-self-referential: compares synthesis against ground-truth
    audio features, no model in the loop (VERDICT r3 item 9)."""
    import numpy as np

    a = np.asarray(mel_a, np.float64) * 4.0 - 4.0      # → log10 mel
    b = np.asarray(mel_b, np.float64) * 4.0 - 4.0
    frames = min(a.shape[0], b.shape[0])
    if frames == 0:
        return float("inf")
    a, b = a[:frames], b[:frames]
    n_mels = a.shape[1]
    k = np.arange(n_mels)[None, :]
    i = np.arange(n_mels)[:, None]
    dct = np.cos(np.pi * i * (2 * k + 1) / (2 * n_mels)) * \
        np.sqrt(2.0 / n_mels)
    dct[0] *= np.sqrt(0.5)
    cep_a = a @ dct.T[:, :n_coeffs + 1]
    cep_b = b @ dct.T[:, :n_coeffs + 1]
    diff = cep_a[:, 1:] - cep_b[:, 1:]                 # drop c0
    # 10*sqrt(2)/ln10 on ln-cepstra == 10*sqrt(2) on log10-cepstra
    return float(10.0 * np.sqrt(2.0) *
                 np.mean(np.sqrt(np.sum(diff ** 2, axis=1))))


# -- 8-bit audio wire format -------------------------------------------------
# G.711-style μ-law companding: the host→device ASR wire carries uint8
# codes (half of int16, quarter of f32) and the device expands them
# inside the fused frontend program.  ~38 dB SNR on speech — above the
# noise floor that matters for log-mel features — at half the
# host→device bytes, which is the pipeline's bottleneck on thin links.

MULAW_MU = 255.0


def mulaw_encode(audio):
    """float [-1, 1] or int16 audio → uint8 μ-law codes (host, numpy)."""
    audio = np.asarray(audio)
    if audio.dtype == np.int16:
        audio = audio.astype(np.float32) / 32768.0
    else:
        audio = np.clip(audio.astype(np.float32), -1.0, 1.0)
    compressed = np.sign(audio) * (
        np.log1p(MULAW_MU * np.abs(audio)) / np.log1p(MULAW_MU))
    return np.round((compressed + 1.0) * 127.5).astype(np.uint8)


def mulaw_decode(codes):
    """uint8 μ-law codes → float32 [-1, 1] (jax — runs on device inside
    the fused frontend, so the wire stays 8-bit end to end)."""
    x = codes.astype(jnp.float32) * (1.0 / 127.5) - 1.0
    return jnp.sign(x) * jnp.expm1(
        jnp.abs(x) * jnp.log1p(MULAW_MU)) * (1.0 / MULAW_MU)


# -- 8-bit mel wire format (ISSUE 6 satellite) -------------------------------
# The ASR wire after the frontend split carries log-mel features: f32
# [T, 80] is 320 bytes per mel frame.  Absmax int8 with one scale PER
# MEL FRAME (row) quantizes each 10 ms slice against its own dynamic
# range — a quiet frame next to a plosive keeps its resolution, unlike
# one whole-chunk scale — at 80 + 4 bytes per frame (3.8× smaller).
# The packed layout rides the generic binary envelope as a single int8
# buffer: [T, num_mels + 4], the trailing 4 bytes per row being the f32
# scale reinterpreted as int8 (transport/wire.py codec tag "i8mel").
# All host-side numpy: the transport never touches the accelerator.

def mel_i8_encode(mel):
    """float [T, M] log-mel → (int8 codes [T, M], float32 scales [T]).
    Non-finite entries saturate (±inf) or zero (NaN) instead of
    poisoning the row's scale."""
    x = np.asarray(mel, dtype=np.float32)
    if x.ndim != 2:
        raise ValueError(f"mel_i8_encode wants [T, M], got {x.shape}")
    finite = np.where(np.isfinite(x), np.abs(x), 0.0)
    scales = finite.max(axis=1) / 127.0 if x.shape[1] else \
        np.zeros((x.shape[0],), np.float32)
    scales = np.where((scales > 0.0) & np.isfinite(scales),
                      scales, 1.0).astype(np.float32)
    bound = 127.0 * scales[:, None]
    x = np.clip(np.nan_to_num(x, nan=0.0, posinf=np.inf,
                              neginf=-np.inf), -bound, bound)
    codes = np.round(x / scales[:, None]).astype(np.int8)
    return codes, scales


def mel_i8_decode(codes, scales):
    """(int8 codes [T, M], float32 scales [T]) → float32 [T, M]."""
    return np.asarray(codes, np.float32) * \
        np.asarray(scales, np.float32)[:, None]


def mel_i8_pack(mel):
    """float [T, M] → packed int8 [T, M + 4] (codes + per-row scale
    bytes) — the single-buffer form the wire envelope ships."""
    codes, scales = mel_i8_encode(mel)
    scale_bytes = scales.view(np.int8).reshape(-1, 4)
    return np.concatenate([codes, scale_bytes], axis=1)


def mel_i8_unpack(packed):
    """packed int8 [T, M + 4] → float32 [T, M] (inverse of
    mel_i8_pack, up to the codec's quantization loss)."""
    packed = np.asarray(packed, np.int8)
    if packed.ndim != 2 or packed.shape[1] < 5:
        raise ValueError(
            f"mel_i8_unpack wants packed [T, M+4], got {packed.shape}")
    codes = packed[:, :-4]
    scales = np.ascontiguousarray(packed[:, -4:]).view(
        np.float32).reshape(-1)
    return mel_i8_decode(codes, scales)


# -- inverse path: spectrogram → waveform (the TTS vocoder leg) --------------

def stft_complex(audio, n_fft: int = WHISPER_N_FFT, hop: int = WHISPER_HOP):
    """audio: [B, T_samples] → complex spectrum [B, T_frames, n_fft//2+1]
    (Hann window, centred — the invertible counterpart of stft())."""
    pad = n_fft // 2
    audio = jnp.pad(audio, ((0, 0), (pad, pad)), mode="reflect")
    num_frames = 1 + (audio.shape[1] - n_fft) // hop
    idx = (jnp.arange(num_frames)[:, None] * hop +
           jnp.arange(n_fft)[None, :])
    frames = audio[:, idx]
    window = jnp.hanning(n_fft + 1)[:-1].astype(audio.dtype)
    return jnp.fft.rfft(frames * window, axis=-1)


def istft(spectrum, n_fft: int = WHISPER_N_FFT, hop: int = WHISPER_HOP):
    """Inverse STFT by windowed overlap-add with COLA normalization.
    spectrum: [B, T_frames, n_fft//2+1] complex → audio [B, T_samples]."""
    frames = jnp.fft.irfft(spectrum, n=n_fft, axis=-1)   # [B, T, n_fft]
    window = jnp.hanning(n_fft + 1)[:-1].astype(frames.dtype)
    frames = frames * window
    batch, num_frames, _ = frames.shape
    length = n_fft + hop * (num_frames - 1)

    # overlap-add via scatter: positions[t] = t*hop + arange(n_fft)
    positions = (jnp.arange(num_frames)[:, None] * hop +
                 jnp.arange(n_fft)[None, :]).reshape(-1)
    flat = frames.reshape(batch, -1)
    audio = jnp.zeros((batch, length), frames.dtype).at[:, positions].add(
        flat)
    # window-square normalization (COLA)
    norm = jnp.zeros((length,), frames.dtype).at[positions].add(
        jnp.tile(window * window, (num_frames,)))
    audio = audio / jnp.maximum(norm, 1e-8)[None, :]
    pad = n_fft // 2
    return audio[:, pad:length - pad]


@functools.lru_cache(maxsize=4)
def mel_inverse_filterbank(num_mels: int = 80, n_fft: int = WHISPER_N_FFT,
                           sample_rate: int = WHISPER_SAMPLE_RATE):
    """Pseudo-inverse of the mel filterbank: [num_mels, n_fft//2+1]
    (numpy constant — same lru_cache/tracer rule as mel_filterbank).

    rcond truncates near-zero singular values: the unregularized pinv
    rings hard in the Slaney linear→log transition region (~1-1.3 kHz),
    turning a 770 Hz tone into a 1.2 kHz dominant on inversion."""
    forward_bank = np.asarray(mel_filterbank(num_mels, n_fft, sample_rate))
    return np.linalg.pinv(forward_bank, rcond=1e-2).astype(np.float32)


def mel_to_linear(log_mel, num_mels: int = 80, n_fft: int = WHISPER_N_FFT,
                  sample_rate: int = WHISPER_SAMPLE_RATE):
    """Invert whisper log-mel normalization back to a linear magnitude
    spectrogram estimate: [B, T, mels] → [B, T, n_fft//2+1]."""
    log10 = log_mel * 4.0 - 4.0                 # undo (x+4)/4
    mels = jnp.power(10.0, log10)               # undo log10
    linear = mels @ jnp.asarray(
        mel_inverse_filterbank(num_mels, n_fft, sample_rate))
    return jnp.sqrt(jnp.maximum(linear, 0.0))   # power → magnitude


def griffin_lim(magnitude, n_iter: int = 32, n_fft: int = WHISPER_N_FFT,
                hop: int = WHISPER_HOP):
    """Phase recovery: magnitude [B, T, n_fft//2+1] → audio [B, samples].
    Classic Griffin-Lim as a lax.fori_loop (static shapes, jits clean)."""
    def project(audio):
        spectrum = stft_complex(audio, n_fft, hop)
        phase = spectrum / jnp.maximum(jnp.abs(spectrum), 1e-8)
        t = min(phase.shape[1], magnitude.shape[1])
        return istft(magnitude[:, :t].astype(jnp.complex64) *
                     phase[:, :t], n_fft, hop)

    audio = istft(magnitude.astype(jnp.complex64), n_fft, hop)

    def body(_, audio):
        return project(audio)

    return jax.lax.fori_loop(0, n_iter, body, audio)
