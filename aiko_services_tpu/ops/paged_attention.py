# Paged decode attention: a pallas TPU kernel that reads K/V straight
# out of the serving block pool through per-slot block tables — vLLM
# PagedAttention's indirection (Kwon et al., SOSP 2023), TPU-flavored
# via scalar-prefetch index maps (ISSUE 16, ROADMAP item 2).
#
# The XLA paged path (serving_paged._gather_views) must materialize a
# slot-major [S, H, T, D] copy of every slot's blocks once per round
# before the attention einsums can run — the one cost plain XLA cannot
# delete, measured as the bulk of the 11.38 ms decode step vs its
# 5.64 ms HBM roofline (BENCH_r05).  Here the block table rides the
# grid as a scalar-prefetch operand, so each grid step DMAs one pool
# block [H, B, D] directly into VMEM: K and V stream through HBM
# exactly once, and nothing slot-major ever exists.
#
# Grid (S, 2, nb), two phases per slot:
#   phase 0  walks K blocks tables[s, j], accumulating masked scores
#            into a VMEM scratch row [Hkv, G*W, nb*B + P]; the last
#            step appends the side-buffer scores, softmaxes the whole
#            row in place, and seeds the accumulator with the side PV
#   phase 1  walks V blocks, accumulating block PV into the f32
#            accumulator, and writes the output on the last step
# The inactive operand's index map parks on an unchanged block index
# (K on tables[s, nb-1] through phase 1, V on tables[s, 0] through
# phase 0), so the pallas pipeline skips those re-fetches — net HBM
# traffic stays one K pass + one V pass.
#
# Numerics discipline (the bit-parity contract with the XLA oracle):
# every elementwise op matches serving._grouped_block_attention /
# serving_paged's extend body exactly — f32 QK dots * scale, int8
# scale treatment, -1e30 masking, jax.nn.softmax over the full row,
# weight casts before the PV dots.  The kernel's extra [t_cap, nb*B)
# columns are masked to -1e30 and contribute exact zeros to the
# softmax sum, so no t_cap re-slice is needed.  Only the dot-product
# ASSOCIATION differs (blockwise vs one full-T contraction), which is
# why the acceptance criterion is greedy TOKEN identity, proven per
# combination in tests/test_paged_kv.py (interpret mode on CPU).
#
# int8 pools ({"q" i8, "s" f32}) fuse their dequant into the dots two
# ways, each matching its oracle:
#   fold_scales=True   (decode/spec steps) — int8 values stay the dot
#       operand, per-position scales fold into scores (K) and weights
#       (V), the serving._kv_planes discipline
#   fold_scales=False  (chunked-prefill extend) — blocks dequantize in
#       VMEM exactly like layers.dequantize_kv_cache before the dots,
#       because the extend oracle attends dequantized rows
#
# Block sizes honour the (8,128)/(16,128)/(32,128) tiling floors only
# at serving shapes (/opt/skills/guides/pallas_guide.md "Tiling
# Constraints"); tests run tiny shapes in interpret mode, hardware
# validation is BENCH_r06's A/B (AIKO_BENCH_LLAMA_KERNEL).

from __future__ import annotations

import functools

__all__ = ["paged_decode_attention"]


def _paged_attn_kernel(*refs, int8: bool, fold: bool, groups: int,
                       width: int, block_tokens: int, side_len: int,
                       scale: float):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if int8:
        (tables_ref, entry_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref,
         k_side_ref, v_side_ref, valid_ref, o_ref, scores, acc) = refs
    else:
        (tables_ref, entry_ref, q_ref, kq_ref, vq_ref,
         k_side_ref, v_side_ref, valid_ref, o_ref, scores, acc) = refs
        ks_ref = vs_ref = None
    del tables_ref                     # consumed by the index maps
    s = pl.program_id(0)
    phase = pl.program_id(1)
    j = pl.program_id(2)
    nb = pl.num_programs(2)
    main_t = nb * block_tokens

    @pl.when(phase == 0)
    def _block_scores():
        q = q_ref[0]                                  # [Hkv, GW, D]
        k = kq_ref[0]                                 # [Hkv, B, D]
        if int8 and not fold:
            # extend-path numerics: cast both factors then multiply in
            # the compute dtype, layers.dequantize_kv_cache verbatim
            k = k.astype(q.dtype) * \
                ks_ref[0][:, :, None].astype(q.dtype)
        else:
            k = k.astype(q.dtype)
        sc = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [Hkv,GW,B]
        if int8 and fold:
            sc = sc * ks_ref[0][:, None, :]
        # absolute position mask — positions past the slot's read-only
        # extent (entry_lengths) are dead cells / null-block zeros
        pos = j * block_tokens + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 2)
        sc = jnp.where(pos < entry_ref[s], sc, -1e30)
        scores[:, :, pl.ds(j * block_tokens, block_tokens)] = sc

    @pl.when((phase == 0) & (j == nb - 1))
    def _side_softmax():
        q = q_ref[0]
        k_s = k_side_ref[0]                           # [Hkv, P, D]
        sc = jax.lax.dot_general(
            q, k_s, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [Hkv,GW,P]
        valid = jnp.broadcast_to(valid_ref[0][None],
                                 (groups, width, side_len))
        valid = valid.reshape(1, groups * width, side_len)
        scores[:, :, main_t:] = jnp.where(valid, sc, -1e30)
        weights = jax.nn.softmax(scores[...], axis=-1)
        scores[...] = weights                # phase 1 reads them back
        v_s = v_side_ref[0]
        acc[...] = jax.lax.dot_general(
            weights[:, :, main_t:].astype(v_s.dtype), v_s,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(phase == 1)
    def _block_pv():
        w = scores[:, :, pl.ds(j * block_tokens, block_tokens)]
        v = vq_ref[0]
        if int8 and not fold:
            v = v.astype(q_ref.dtype) * \
                vs_ref[0][:, :, None].astype(q_ref.dtype)
        else:
            if int8:
                w = w * vs_ref[0][:, None, :]
            v = v.astype(q_ref.dtype)
        acc[...] += jax.lax.dot_general(
            w.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when((phase == 1) & (j == nb - 1))
    def _finish():
        o_ref[0] = acc[...]


def paged_decode_attention(q, k_pool, v_pool, tables, k_side, v_side,
                           side_valid, entry_lengths, *, groups: int,
                           scale: float | None = None,
                           fold_scales: bool = True,
                           interpret: bool | None = None):
    """Block-table-native decode attention over a paged KV pool.

    q:            [S, Hkv, G*W, D] grouped queries (G-major: the
                  (group, width) axes flattened)
    k/v_pool:     per-layer pool leaf [N, Hkv, B, D], or the int8
                  serving dict {"q" i8 [N, Hkv, B, D], "s" f32
                  [N, Hkv, B]}
    tables:       [S, nb] int32 block ids (nb * B >= the slot's
                  readable extent; unfilled entries point at the null
                  block and are masked)
    k/v_side:     [S, Hkv, P, D] this round's side buffers in the
                  compute dtype
    side_valid:   [S, W, P] bool — per-query side visibility, computed
                  by the caller (this is what widens the speculative
                  verify into the same kernel: W = 1 + k and the
                  pos_side <= q_pos mask arrive here unchanged)
    entry_lengths: [S] int32 read-only main extent per slot

    Returns [S, Hkv, G*W, D] f32.  interpret=None auto-selects:
    compiled pallas on TPU, interpreter mode elsewhere (CPU tests run
    the same kernel code path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..models.layers import paged_pool_planes

    kq, k_scales = paged_pool_planes(k_pool)
    vq, v_scales = paged_pool_planes(v_pool)
    int8 = k_scales is not None
    slots_n, num_kv, gw, head_dim = q.shape
    width = gw // groups
    nb = tables.shape[1]
    block_tokens = kq.shape[2]
    side_len = k_side.shape[2]
    if scale is None:
        # f32(1)/sqrt(f32(d)) — the exact value the oracle's traced
        # 1/jnp.sqrt computes, so the score scaling cannot drift a ulp
        scale = float(np.float32(1.0) / np.sqrt(np.float32(head_dim)))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def at_slot(s, p, j, tables, entries):
        return (s, 0, 0, 0)

    def valid_map(s, p, j, tables, entries):
        return (s, 0, 0)

    def k_map(s, p, j, tables, entries):
        # phase 0 walks the K blocks; phase 1 parks on the last one so
        # consecutive grid steps keep an unchanged block index and the
        # pipeline skips the re-fetch
        return (jax.lax.select(p == 0, tables[s, j],
                               tables[s, nb - 1]), 0, 0, 0)

    def v_map(s, p, j, tables, entries):
        # mirror image: V parks on block 0 through phase 0
        return (jax.lax.select(p == 0, tables[s, 0],
                               tables[s, j]), 0, 0, 0)

    def k_scale_map(s, p, j, tables, entries):
        return k_map(s, p, j, tables, entries)[:3]

    def v_scale_map(s, p, j, tables, entries):
        return v_map(s, p, j, tables, entries)[:3]

    block_kv = (1, num_kv, block_tokens, head_dim)
    in_specs = [pl.BlockSpec((1, num_kv, gw, head_dim), at_slot),
                pl.BlockSpec(block_kv, k_map)]
    operands = [q, kq]
    if int8:
        in_specs.append(
            pl.BlockSpec((1, num_kv, block_tokens), k_scale_map))
        operands.append(k_scales)
    in_specs.append(pl.BlockSpec(block_kv, v_map))
    operands.append(vq)
    if int8:
        in_specs.append(
            pl.BlockSpec((1, num_kv, block_tokens), v_scale_map))
        operands.append(v_scales)
    in_specs += [pl.BlockSpec((1, num_kv, side_len, head_dim), at_slot),
                 pl.BlockSpec((1, num_kv, side_len, head_dim), at_slot),
                 pl.BlockSpec((1, width, side_len), valid_map)]
    operands += [k_side, v_side, side_valid]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots_n, 2, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, num_kv, gw, head_dim), at_slot),
        scratch_shapes=[
            pltpu.VMEM((num_kv, gw, nb * block_tokens + side_len),
                       jnp.float32),
            pltpu.VMEM((num_kv, gw, head_dim), jnp.float32),
        ])
    kernel = functools.partial(
        _paged_attn_kernel, int8=int8, fold=fold_scales, groups=groups,
        width=width, block_tokens=block_tokens, side_len=side_len,
        scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (slots_n, num_kv, gw, head_dim), jnp.float32),
        interpret=interpret,
    )(tables.astype(jnp.int32), entry_lengths.astype(jnp.int32),
      *operands)
