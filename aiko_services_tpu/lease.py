# Lease: the framework-wide timeout primitive.
# (capability parity: aiko_services/lease.py:31-83 — expire/extend handlers,
# optional automatic extension at 0.8x of the lease period)

from __future__ import annotations

__all__ = ["Lease"]

_EXTEND_FACTOR = 0.8


class Lease:
    def __init__(self, engine, lease_time: float, lease_id,
                 lease_expired_handler=None, lease_extend_handler=None,
                 automatic_extend: bool = False):
        self.event = engine
        self.lease_time = lease_time
        self.lease_id = lease_id
        self.lease_expired_handler = lease_expired_handler
        self.lease_extend_handler = lease_extend_handler
        self.automatic_extend = automatic_extend
        self.expired = False
        self._timer = None
        self._schedule()

    def _schedule(self) -> None:
        if self._timer is not None:
            self.event.remove_timer_handler(self._timer)
        delay = self.lease_time * _EXTEND_FACTOR if self.automatic_extend \
            else self.lease_time
        self._timer = self.event.add_oneshot_handler(self._fire, delay)

    def _fire(self) -> None:
        self._timer = None
        if self.expired:
            return
        if self.automatic_extend:
            if self.lease_extend_handler:
                self.lease_extend_handler(self.lease_time, self.lease_id)
            self._schedule()
        else:
            self.expired = True
            if self.lease_expired_handler:
                self.lease_expired_handler(self.lease_id)

    def extend(self, lease_time: float | None = None) -> None:
        if self.expired:
            return
        if lease_time is not None:
            self.lease_time = lease_time
        self._schedule()

    def cancel(self) -> None:
        """Retire the lease NOW: the timer is removed and neither the
        expired nor the extend handler will ever fire again.  Every code
        path that stops caring about a lease (reply arrived, stream
        destroyed, proxy re-resolved) must call this — an uncancelled
        timer on a dead hop fires an expired handler into state that no
        longer exists."""
        self.expired = True
        if self._timer is not None:
            self.event.remove_timer_handler(self._timer)
            self._timer = None

    # historical name; cancel() is the explicit spelling
    terminate = cancel

    @property
    def active(self) -> bool:
        return not self.expired
