# Connection-state ladder for a process's control-plane link.
# (capability parity: aiko_services/connection.py:12-46 — ordered states,
# "is_connected(state)" means at-or-above, handler fan-out on change)

from __future__ import annotations

from enum import IntEnum

__all__ = ["ConnectionState", "Connection"]


class ConnectionState(IntEnum):
    NONE = 0          # no connectivity
    NETWORK = 1       # host networking up
    BOOTSTRAP = 2     # broker located
    TRANSPORT = 3     # transport connected
    REGISTRAR = 4     # registrar discovered — fully joined


class Connection:
    def __init__(self):
        self._state = ConnectionState.NONE
        self._handlers = []

    @property
    def state(self) -> ConnectionState:
        return self._state

    def is_connected(self, at_least: ConnectionState) -> bool:
        return self._state >= at_least

    def add_handler(self, handler) -> None:
        """handler(connection, state); fired immediately with current state."""
        self._handlers.append(handler)
        handler(self, self._state)

    def remove_handler(self, handler) -> None:
        if handler in self._handlers:
            self._handlers.remove(handler)

    def update(self, state: ConnectionState) -> None:
        if state == self._state:
            return
        self._state = state
        for handler in list(self._handlers):
            handler(self, state)
