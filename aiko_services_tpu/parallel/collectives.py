# Collective communication: the tensor-path replacement for the reference's
# MQTT data plane.
#
# The reference moves tensors as zlib+np.save blobs through a broker
# (reference: aiko_services/elements/audio_io.py:392-439); here co-located
# elements exchange jax.Arrays and cross-chip movement is XLA collectives
# over ICI/DCN.  These wrappers exist so runtime code (schedulers, pipeline
# data plane) has one seam for device communication — inside shard_map they
# are the jax.lax collectives; outside they are sharding-aware transfers.

from __future__ import annotations

__all__ = ["psum", "pmean", "pmax", "all_gather", "ppermute_ring",
           "reduce_scatter", "axis_index", "axis_size", "device_transfer",
           "ring_neighbours"]


def psum(x, axis_name):
    import jax
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    import jax
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    import jax
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis: int = 0):
    import jax
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def axis_index(axis_name):
    import jax
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name):
    import jax
    return jax.lax.psum(1, axis_name)


def ring_neighbours(n: int, reverse: bool = False):
    """Permutation table sending shard j → j+1 (mod n); the ICI ring."""
    if reverse:
        return [(j, (j - 1) % n) for j in range(n)]
    return [(j, (j + 1) % n) for j in range(n)]


def ppermute_ring(x, axis_name, n: int, reverse: bool = False):
    """Rotate x one hop around the ring of `axis_name` (ring attention,
    pipeline-parallel stage handoff)."""
    import jax
    return jax.lax.ppermute(x, axis_name,
                            perm=ring_neighbours(n, reverse))


def device_transfer(x, sharding):
    """Host-side: move/reshard an array (async under the hood — jax
    dispatches eagerly and the transfer overlaps host code)."""
    import jax
    return jax.device_put(x, sharding)
