# Collective communication: the tensor-path replacement for the reference's
# MQTT data plane.
#
# The reference moves tensors as zlib+np.save blobs through a broker
# (reference: aiko_services/elements/audio_io.py:392-439); here co-located
# elements exchange jax.Arrays and cross-chip movement is XLA collectives
# over ICI/DCN.  These wrappers exist so runtime code (schedulers, pipeline
# data plane) has one seam for device communication — inside shard_map they
# are the jax.lax collectives; outside they are sharding-aware transfers.

from __future__ import annotations

__all__ = ["psum", "pmean", "pmax", "all_gather", "ppermute_ring",
           "reduce_scatter", "axis_index", "axis_size", "device_transfer",
           "ring_neighbours", "shard_map"]


def shard_map(fn, mesh, in_specs, out_specs, check_vma=None):
    """Version-tolerant shard_map: jax.shard_map on current jax (its
    own defaults preserved), the jax.experimental spelling on older
    toolchains — there with the replication checker OFF, because old
    checkers lack the varying-manifest ops (pcast/pvary) this code
    marks loop carries with."""
    import jax
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      check_rep=bool(check_vma))


def pcast_varying(x, axis_name):
    """Mark x device-varying for the shard_map type system.  No-op on
    jax versions without the varying-manifest checker (their shard_map
    runs with the replication check off — see shard_map above)."""
    import jax
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_name)
    return x


def psum(x, axis_name):
    import jax
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    import jax
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    import jax
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis: int = 0):
    import jax
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def axis_index(axis_name):
    import jax
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name):
    import jax
    return jax.lax.psum(1, axis_name)


def ring_neighbours(n: int, reverse: bool = False):
    """Permutation table sending shard j → j+1 (mod n); the ICI ring."""
    if reverse:
        return [(j, (j - 1) % n) for j in range(n)]
    return [(j, (j + 1) % n) for j in range(n)]


def ppermute_ring(x, axis_name, n: int, reverse: bool = False):
    """Rotate x one hop around the ring of `axis_name` (ring attention,
    pipeline-parallel stage handoff)."""
    import jax
    return jax.lax.ppermute(x, axis_name,
                            perm=ring_neighbours(n, reverse))


def device_transfer(x, sharding):
    """Host-side: move/reshard an array (async under the hood — jax
    dispatches eagerly and the transfer overlaps host code)."""
    import jax
    return jax.device_put(x, sharding)


# -- mesh-aware helpers -------------------------------------------------------
# The two-plane design (SURVEY.md §5.8) needs host-side answers to "what
# does this collective cost and which fabric does it ride": axes whose
# devices share a host ride ICI; axes spanning hosts ride DCN.  Shardings
# should be laid out so the high-frequency axes (tensor/expert) are
# ICI-local and only data/pipeline axes cross DCN.

def axis_fabric(mesh, axis_name: str) -> str:
    """"ici" if every device along `axis_name` (for each fixed point of
    the other axes) lives on one host/process, else "dcn"."""
    import numpy as np

    axes = list(mesh.shape.keys())
    index = axes.index(axis_name)
    devices = np.moveaxis(mesh.devices, index, -1)
    for row in devices.reshape(-1, devices.shape[-1]):
        hosts = {getattr(d, "process_index", 0) for d in row}
        if len(hosts) > 1:
            return "dcn"
    return "ici"


def mesh_fabric_report(mesh) -> dict:
    """axis name → "ici"|"dcn" for every mesh axis (EC-shareable: the
    lifecycle manager and dashboard surface it as device-pool health)."""
    return {axis: axis_fabric(mesh, axis) for axis in mesh.shape.keys()}


def reshard(x, mesh, partition_spec):
    """Reshard an array onto `mesh` with a PartitionSpec — the host-side
    boundary transfer for cross-runtime tensor handoff (replaces the
    reference's zlib+np.save MQTT hop for co-scheduled runtimes)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.device_put(x, NamedSharding(mesh, partition_spec))


def collective_bytes(x, axis_name, mesh, op: str = "all_gather") -> int:
    """Wire-byte estimate for a collective over `axis_name` — ring
    algorithms move ~(n-1)/n of the payload per hop; all_gather/
    reduce_scatter move the full gathered size, psum ~2x scatter.  Used
    by schedulers to choose batch shapes that keep collectives on ICI."""
    import numpy as np

    n = mesh.shape[axis_name]
    item_bytes = int(np.prod(x.shape)) * x.dtype.itemsize
    if op in ("all_gather",):
        return item_bytes * (n - 1)
    if op in ("reduce_scatter",):
        return item_bytes * (n - 1) // n
    if op in ("psum", "all_reduce"):
        return 2 * item_bytes * (n - 1) // n
    if op in ("ppermute", "ring"):
        return item_bytes
    raise ValueError(f"unknown collective {op!r}")
