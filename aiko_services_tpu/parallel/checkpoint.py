# Checkpoint / resume for parameter and training state.
#
# The reference has NO checkpointing (SURVEY.md §5.4 "Absent"); its only
# durable state is MQTT retained messages.  Here model/training state is a
# first-class artifact: orbax (async-capable, sharding-aware) when
# available, flat-npz fallback otherwise — the same '/'-joined key scheme
# the speech element's weight loader reads, so checkpoints and weight
# files interop.

from __future__ import annotations

import json
import os
import re

__all__ = ["save_checkpoint", "restore_checkpoint", "CheckpointManager",
           "flatten_tree", "unflatten_into"]


def flatten_tree(tree, prefix="") -> dict:
    """pytree → {'/'-joined path: leaf} (dicts/lists only)."""
    flat = {}
    if isinstance(tree, dict):
        items = tree.items()
    elif isinstance(tree, (list, tuple)):
        items = enumerate(tree)
    else:
        return {prefix.rstrip("/"): tree}
    for key, value in items:
        path = f"{prefix}{key}"
        if isinstance(value, (dict, list, tuple)):
            flat.update(flatten_tree(value, prefix=f"{path}/"))
        else:
            flat[path] = value
    return flat


def unflatten_into(template, flat: dict):
    """Rebuild a tree shaped like `template` from flatten_tree output;
    every template leaf must be present."""
    def build(node, prefix=""):
        if isinstance(node, dict):
            return {k: build(v, f"{prefix}{k}/") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            rebuilt = [build(v, f"{prefix}{i}/")
                       for i, v in enumerate(node)]
            if isinstance(node, tuple):
                # namedtuples (optax states) construct from *args
                if hasattr(node, "_fields"):
                    return type(node)(*rebuilt)
                return tuple(rebuilt)
            return rebuilt
        key = prefix.rstrip("/")
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf: {key}")
        return flat[key]
    return build(template)


def save_checkpoint(directory: str, tree, step: int | None = None) -> str:
    """Write `tree` under `directory` (npz + manifest).  Returns the
    checkpoint path."""
    import numpy as np

    name = f"step_{step}" if step is not None else "checkpoint"
    path = os.path.join(directory, name)
    os.makedirs(path, exist_ok=True)
    flat = flatten_tree(tree)
    arrays = {key: np.asarray(value) for key, value in flat.items()}
    np.savez(os.path.join(path, "state.npz"), **arrays)
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump({"step": step, "leaves": len(arrays)}, f)
    return path


def restore_checkpoint(path: str, template):
    """Load a save_checkpoint dir back into `template`'s structure with
    each leaf cast to the template leaf's dtype."""
    import numpy as np

    data = np.load(os.path.join(path, "state.npz"))
    flat = {}
    for key in data.files:
        flat[key] = data[key]

    def cast(leaf, loaded):
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None and hasattr(loaded, "astype"):
            if tuple(getattr(leaf, "shape", ())) != tuple(loaded.shape):
                raise ValueError(
                    f"checkpoint leaf shape {loaded.shape} != template "
                    f"{tuple(leaf.shape)}")
            return loaded.astype(dtype)
        # scalar python leaf (e.g. step counter)
        return loaded.item() if hasattr(loaded, "item") and \
            loaded.shape == () else loaded

    template_flat = flatten_tree(template)
    restored = {key: cast(template_flat[key], flat[key])
                for key in template_flat}
    return unflatten_into(template, restored)


class CheckpointManager:
    """Step-numbered checkpoints with retention (keep latest N)."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _steps(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            match = re.fullmatch(r"step_(\d+)", name)
            if match:
                steps.append(int(match.group(1)))
        return sorted(steps)

    def save(self, tree, step: int) -> str:
        path = save_checkpoint(self.directory, tree, step)
        for old in self._steps()[:-self.keep]:
            old_path = os.path.join(self.directory, f"step_{old}")
            import shutil
            shutil.rmtree(old_path, ignore_errors=True)
        return path

    def latest_step(self) -> int | None:
        steps = self._steps()
        return steps[-1] if steps else None

    def restore_latest(self, template):
        step = self.latest_step()
        if step is None:
            return None, None
        path = os.path.join(self.directory, f"step_{step}")
        return restore_checkpoint(path, template), step
