# Sharding rules: logical tensor axes → mesh axes → NamedSharding.
#
# Models annotate parameters/activations with LOGICAL axis names
# ("embed", "heads", "batch", ...); a ShardingRules table maps those to
# physical mesh axes; XLA inserts the collectives.  This indirection is what
# lets one model definition run DP-only on 1 chip, TP over 8, or DP×TP over
# a pod without touching model code (scaling-book recipe; no reference
# counterpart — the reference has no tensor path at all, SURVEY.md §2).

from __future__ import annotations

from dataclasses import dataclass, field

from .mesh import AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_SEQUENCE

__all__ = ["ShardingRules", "DEFAULT_RULES", "named_sharding",
           "shard_pytree", "constrain", "replicated"]


@dataclass
class ShardingRules:
    """logical axis name → mesh axis name (or None = replicate)."""
    rules: dict = field(default_factory=dict)

    def spec(self, *logical_axes) -> "jax.sharding.PartitionSpec":
        from jax.sharding import PartitionSpec
        return PartitionSpec(
            *(self.rules.get(axis) for axis in logical_axes))

    def with_overrides(self, **overrides) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(overrides)
        return ShardingRules(merged)


# The standard megatron-style layout:
#   batch over data axis; attention heads + ffn hidden over model axis;
#   embed/ffn-in replicated within a TP group; sequence over seq axis for
#   context parallelism; experts over the expert axis.
DEFAULT_RULES = ShardingRules({
    "batch": AXIS_DATA,
    "sequence": AXIS_SEQUENCE,
    "heads": AXIS_MODEL,
    "kv_heads": AXIS_MODEL,
    "embed": None,
    "head_dim": None,
    "ffn": AXIS_MODEL,
    "vocab": AXIS_MODEL,
    "expert": AXIS_EXPERT,
    "channels": None,
})


def named_sharding(mesh, *logical_axes, rules: ShardingRules = None):
    from jax.sharding import NamedSharding
    rules = rules or DEFAULT_RULES
    spec = rules.spec(*logical_axes)
    # drop mesh axes the mesh doesn't have (e.g. TP rules on a DP-only mesh)
    from jax.sharding import PartitionSpec
    cleaned = PartitionSpec(
        *(axis if axis in mesh.axis_names else None for axis in spec))
    return NamedSharding(mesh, cleaned)


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def shard_pytree(tree, axes_tree, mesh, rules: ShardingRules = None):
    """Place a parameter pytree onto the mesh.

    axes_tree mirrors `tree`, each leaf a tuple of logical axis names (or
    None = replicate).  Returns the tree with jax.device_put applied."""
    import jax

    def place(leaf, axes):
        if axes is None:
            return jax.device_put(leaf, replicated(mesh))
        return jax.device_put(
            leaf, named_sharding(mesh, *axes, rules=rules))

    return jax.tree.map(place, tree, axes_tree,
                        is_leaf=lambda x: x is None)


def constrain(x, mesh, *logical_axes, rules: ShardingRules = None):
    """with_sharding_constraint under logical names (no-op off-mesh)."""
    import jax

    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, *logical_axes, rules=rules))
