# Ring attention: exact attention over sequences sharded across devices.
#
# Long-context / sequence-parallel support the reference entirely lacks
# (SURVEY.md §5.7: no attention code at all).  Design follows blockwise ring
# attention (Liu et al.): Q stays resident, K/V blocks rotate around the
# sequence-axis ring via ppermute (one ICI hop per step), and softmax is
# accumulated online (running max / normalizer), so the full S×S score
# matrix never materializes and memory is O(S_local²) per device.
#
# XLA overlaps the ppermute with the local block's compute, so on a TPU
# ring the collective cost hides behind the matmuls for realistic shapes.

from __future__ import annotations

import functools
import math

from .mesh import AXIS_SEQUENCE

__all__ = ["ring_attention", "ring_attention_sharded", "attention_reference"]


def _block_update(q, k, v, o, m, l, q_offset, k_offset, causal, scale):
    """One online-softmax accumulation step against a K/V block.

    q: [B,H,Sq,D]  k,v: [B,H,Sk,D]  o: [B,H,Sq,D]  m,l: [B,H,Sq]
    offsets are the blocks' global sequence positions (for causal masks)."""
    import jax.numpy as jnp
    from jax import lax

    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        scores = jnp.where(k_pos <= q_pos, scores, -jnp.inf)

    block_max = jnp.max(scores, axis=-1)                    # [B,H,Sq]
    m_new = jnp.maximum(m, block_max)
    # fully-masked block: keep accumulators untouched (exp(-inf)=0 paths)
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def ring_attention_sharded(q, k, v, axis_name: str = AXIS_SEQUENCE,
                           causal: bool = False, scale: float | None = None):
    """The per-device body — call inside shard_map with the sequence axis
    sharded over `axis_name`.  q,k,v: [B, H, S_local, D].

    GQA-aware: k/v may carry fewer heads than q (H_q % H_kv == 0).  The
    ring rotates the SMALL K/V blocks — expansion to H_q happens
    transiently per block, so ICI bytes and resident K/V stay at
    H_kv size."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    group = q.shape[1] // k.shape[1]
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_offset = my_idx * s_local

    # derive accumulators from q so they carry q's device-varying axes
    # (shard_map type system: the fori_loop carry must match its output,
    # which varies over every mesh axis q is sharded on)
    zeros = (q * 0).astype(jnp.float32)
    o = zeros                                       # [B,H,Sq,D]
    l = jnp.sum(zeros, axis=-1)                     # [B,H,Sq] zeros
    m = l - jnp.inf                                 # [B,H,Sq] -inf
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        kv_idx = (my_idx - i) % n         # whose block we hold at step i
        if group > 1:                     # GQA: expand per block only
            k_full = jnp.repeat(k_blk, group, axis=1)
            v_full = jnp.repeat(v_blk, group, axis=1)
        else:
            k_full, v_full = k_blk, v_blk
        o, m, l = _block_update(q, k_full, v_full, o, m, l,
                                q_offset, kv_idx * s_local, causal, scale)
        # rotate K/V one hop; XLA overlaps this with the next iteration's
        # compute on TPU (skipped after the last block)
        k_blk, v_blk = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_blk, v_blk))
        return o, m, l, k_blk, v_blk

    o, m, l, _, _ = lax.fori_loop(0, n, step, (o, m, l, k, v))
    l = jnp.where(l == 0.0, 1.0, l)       # fully-masked rows → zeros
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = AXIS_SEQUENCE,
                   batch_axis: str | None = "data", causal: bool = False,
                   scale: float | None = None):
    """Sequence-parallel exact attention.

    q, k, v: [B, H, S, D] with S sharded over `axis_name` (and optionally B
    over `batch_axis`) on `mesh`.  Returns [B, H, S, D] with the same
    sharding."""
    import jax
    from jax.sharding import PartitionSpec as P

    batch = batch_axis if (batch_axis in mesh.axis_names) else None
    spec = P(batch, None, axis_name, None)
    body = functools.partial(ring_attention_sharded, axis_name=axis_name,
                             causal=causal, scale=scale)
    from .collectives import shard_map
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def attention_reference(q, k, v, causal: bool = False,
                        scale: float | None = None):
    """Plain full attention — correctness oracle for the ring version."""
    import jax.numpy as jnp
    from jax import lax, nn

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        scores = jnp.where(k_pos <= q_pos, scores, -jnp.inf)
    weights = nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
