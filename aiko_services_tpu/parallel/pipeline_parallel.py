# Pipeline parallelism: stages on distinct device groups, frames in
# flight overlapping.
#
# The reference's "pipeline parallelism" is a dataflow graph across OS
# processes with strictly sequential per-frame execution
# (reference: aiko_services/pipeline.py:650-712); SURVEY.md §2's
# obligations table requires TRUE PP here: each stage compiled onto its
# own device group, inter-stage handoffs as device-to-device transfers,
# and frame k+1 entering stage 0 while frame k occupies stage 1 — jax's
# async dispatch provides the overlap, device_put the ICI hop.
#
# Two granularities:
#   * StagedExecutor — inference PP for element pipelines: each stage is a
#     jitted fn pinned to a device group; submit() returns immediately
#     (device futures), so consecutive frames overlap across stages.
#   * gpipe_spmd — training-style PP inside one jit: stage weights sharded
#     over the "stage" mesh axis, microbatches rotated with ppermute
#     (GPipe schedule as a shard_map collective program).

from __future__ import annotations

from .mesh import AXIS_STAGE

__all__ = ["StagedExecutor", "stage_device_groups", "gpipe_spmd"]


def stage_device_groups(devices, num_stages: int):
    """Split a device list into contiguous per-stage groups (contiguous =
    neighbouring ICI links carry the inter-stage traffic)."""
    devices = list(devices)
    if len(devices) % num_stages:
        raise ValueError(f"{len(devices)} devices not divisible into "
                         f"{num_stages} stages")
    per_stage = len(devices) // num_stages
    return [devices[i * per_stage:(i + 1) * per_stage]
            for i in range(num_stages)]


class StagedExecutor:
    """Inference pipeline parallelism over device groups.

    stages: list of (fn, params) — fn(params, x) -> y, jitted per stage
    and pinned to its group's first device (single-device groups) or
    sharded submesh.  submit(x) dispatches asynchronously: jax enqueues
    the whole chain without blocking the host, so multiple frames occupy
    different stages concurrently; result(y) blocks for the value."""

    def __init__(self, stages, devices=None, donate: bool = False):
        import jax

        devices = list(devices if devices is not None else jax.devices())
        self.groups = stage_device_groups(devices, len(stages))
        self._fns = []
        self._params = []
        for (fn, params), group in zip(stages, self.groups):
            device = group[0]
            # placement follows the arguments: params live on the stage's
            # device and submit() device_puts x there, so jit compiles and
            # runs each stage on its group without the deprecated
            # jit(device=...) pin
            compiled = jax.jit(fn, donate_argnums=(1,) if donate else ())
            self._fns.append(compiled)
            self._params.append(jax.device_put(params, device))
        self.in_flight = 0

    def submit(self, x):
        """Enqueue one frame through all stages; returns the (device-
        resident, still-computing) final value immediately.  in_flight
        counts frames submitted but not yet collect()ed — the occupancy
        the dashboard/EC shares report."""
        import jax

        for index, fn in enumerate(self._fns):
            x = jax.device_put(x, self.groups[index][0])
            x = fn(self._params[index], x)
        self.in_flight += 1
        return x

    def collect(self, y):
        """Block for a submitted frame's value (host numpy) and retire it
        from the in-flight count."""
        value = self.result(y)
        self.in_flight = max(0, self.in_flight - 1)
        return value

    @staticmethod
    def result(y):
        """Block for a submitted frame's value (host numpy) without
        touching occupancy bookkeeping.  Stage outputs may be pytrees
        (e.g. a decode stage's (tokens, lengths, scores))."""
        import jax
        import numpy as np

        return jax.tree.map(np.asarray, y)

    def map(self, frames):
        """Pipeline a sequence: submit everything (filling all stages),
        then collect in order."""
        pending = [self.submit(frame) for frame in frames]
        return [self.collect(y) for y in pending]


def gpipe_spmd(stage_fn, mesh, num_microbatches: int,
               axis_name: str = AXIS_STAGE):
    """Build a GPipe-style SPMD step: weights sharded over the stage axis,
    microbatches streamed through with ppermute.

    stage_fn(stage_params, x) -> y must map one stage's computation; all
    stages share this code (uniform layers — the transformer case).

    Returns step(stage_params_stacked, microbatches) where
      stage_params_stacked: pytree with leading axis = num_stages, sharded
        over `axis_name`;
      microbatches: [num_microbatches, batch, ...] (replicated input);
    output: [num_microbatches, batch, ...] after every stage has processed
    every microbatch (activations rotate stage→stage over ICI)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    num_stages = mesh.shape[axis_name]

    def spmd(stage_params, microbatches):
        # stage_params leaves: [1, ...] (this stage's slice)
        params = jax.tree.map(lambda leaf: leaf[0], stage_params)
        stage_idx = jax.lax.axis_index(axis_name)
        n = num_microbatches
        steps = n + num_stages - 1
        perm = [(j, (j + 1) % num_stages) for j in range(num_stages)]

        # mark the loop state stage-varying up front (shard_map type
        # system: the fori_loop carry type must match its output)
        from .collectives import pcast_varying
        buffer = pcast_varying(microbatches, axis_name)
        carry = jnp.zeros_like(buffer[0])

        def step_fn(t, state):
            buffer, carry = state
            # stage 0 ingests microbatch t; others take the rotated carry
            mb_index = jnp.clip(t, 0, n - 1)
            x = jnp.where(stage_idx == 0, buffer[mb_index], carry)
            y = stage_fn(params, x)
            # emit: the LAST stage's result for microbatch (t - S + 1)
            out_index = jnp.clip(t - (num_stages - 1), 0, n - 1)
            done = (stage_idx == num_stages - 1) & \
                   (t >= num_stages - 1) & (t - (num_stages - 1) < n)
            buffer = jnp.where(
                done,
                jax.lax.dynamic_update_index_in_dim(buffer, y, out_index,
                                                    0),
                buffer)
            carry = jax.lax.ppermute(y, axis_name, perm)
            return buffer, carry

        buffer, _ = jax.lax.fori_loop(0, steps, step_fn, (buffer, carry))
        # only the last stage holds the final outputs: broadcast them
        result = jax.lax.psum(
            jnp.where(stage_idx == num_stages - 1, buffer, 0.0),
            axis_name)
        return result

    from .collectives import shard_map
    return jax.jit(shard_map(
        spmd, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P()))
