# Device mesh management: the TPU pod is the device pool.
#
# The reference has no parallelism substrate at all (SURVEY.md §2: its only
# distribution primitive is MQTT pub/sub; reference aiko_services/message/
# mqtt.py).  This module is the TPU-native replacement's foundation: a
# jax.sharding.Mesh over the slice/pod with named axes for data, model
# (tensor), sequence and expert parallelism; collectives ride ICI inside a
# slice and DCN across slices (scaling-book recipe: pick a mesh, annotate
# shardings, let XLA insert collectives).

from __future__ import annotations

import math
from dataclasses import dataclass, field

__all__ = ["AXIS_DATA", "AXIS_MODEL", "AXIS_SEQUENCE", "AXIS_EXPERT",
           "AXIS_STAGE", "MeshSpec", "create_mesh", "single_device_mesh",
           "best_mesh_shape"]

# Canonical mesh axis names.  Shardings and models refer to these, so a
# pipeline definition only has to pick sizes.
AXIS_DATA = "data"          # batch / replica axis (DP)
AXIS_MODEL = "model"        # tensor-parallel axis (TP over ICI)
AXIS_SEQUENCE = "seq"       # sequence/context-parallel axis (ring attention)
AXIS_EXPERT = "expert"      # expert-parallel axis (MoE)
AXIS_STAGE = "stage"        # pipeline-parallel stage axis


@dataclass
class MeshSpec:
    """Declarative mesh request: axis name → size.  Size -1 on at most one
    axis means "all remaining devices"."""
    axes: dict = field(default_factory=dict)

    def resolve(self, device_count: int) -> dict:
        axes = {k: v for k, v in self.axes.items() if v != 1 or len(
            self.axes) == 1}
        wildcard = [k for k, v in axes.items() if v == -1]
        if len(wildcard) > 1:
            raise ValueError("at most one axis may be -1")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if wildcard:
            if device_count % fixed:
                raise ValueError(
                    f"cannot fill axis {wildcard[0]}: {device_count} devices "
                    f"not divisible by {fixed}")
            axes[wildcard[0]] = device_count // fixed
        elif fixed != device_count:
            raise ValueError(
                f"mesh {axes} wants {fixed} devices, have {device_count}")
        return axes


def best_mesh_shape(device_count: int, model_parallel: int = 1) -> dict:
    """Default 2D layout: model axis innermost (contiguous devices share the
    fastest ICI links for TP collectives), data axis over the rest."""
    if device_count % model_parallel:
        raise ValueError(f"{device_count} devices not divisible by "
                         f"model_parallel={model_parallel}")
    return {AXIS_DATA: device_count // model_parallel,
            AXIS_MODEL: model_parallel}


def create_mesh(axes: dict | MeshSpec | None = None, devices=None):
    """Build a jax.sharding.Mesh.

    axes: {"data": 4, "model": 2} (ordering = mesh dims, model-like axes
    should be last/innermost for ICI locality).  None → 1D data mesh over
    all devices.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {AXIS_DATA: len(devices)}
    if isinstance(axes, MeshSpec):
        axes = axes.resolve(len(devices))
    elif isinstance(axes, dict):
        axes = MeshSpec(dict(axes)).resolve(len(devices))
    return _make_mesh(tuple(axes.values()), tuple(axes.keys()), devices)


def _make_mesh(shape: tuple, names: tuple, devices):
    """Version-tolerant mesh construction.  Auto axis types: shardings
    propagate from annotations (with_sharding_constraint) rather than
    the explicit-sharding type system — the classic pjit programming
    model.  Older jax (< AxisType) defaults to exactly that, so the
    argument is simply omitted there."""
    import jax

    axis_type = getattr(jax.sharding, "AxisType", None)
    if axis_type is not None:
        return jax.make_mesh(shape, names, devices=devices,
                             axis_types=(axis_type.Auto,) * len(names))
    if hasattr(jax, "make_mesh"):
        return jax.make_mesh(shape, names, devices=devices)
    import numpy as np
    return jax.sharding.Mesh(
        np.asarray(devices).reshape(shape), names)


def single_device_mesh(axis: str = AXIS_DATA):
    """1×1 mesh: lets single-chip code paths share the sharded code path."""
    import jax

    return _make_mesh((1,), (axis,), jax.devices()[:1])
