# Sharded training step: the SPMD "one step" the whole framework hangs off.
#
# No reference counterpart (the reference is inference-only glue; SURVEY.md
# §2).  Recipe (scaling book): place params on the mesh via their logical
# axes, shard the batch over the data axis, jit the whole
# loss→grad→optimizer update — XLA inserts the gradient psums over the data
# axis and the TP collectives over the model axis from the shardings alone.

from __future__ import annotations

from .sharding import named_sharding, shard_pytree

__all__ = ["make_train_step", "cross_entropy_loss", "TrainState",
           "init_train_state"]


class TrainState:
    """Minimal train state: params + optimizer state + step counter."""

    def __init__(self, params, opt_state, step=0):
        self.params = params
        self.opt_state = opt_state
        self.step = step

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)


def _register():
    import jax
    try:
        jax.tree_util.register_pytree_node(
            TrainState, lambda s: s.tree_flatten(),
            TrainState.tree_unflatten)
    except ValueError:
        pass        # already registered


_register()


def cross_entropy_loss(logits, targets, mask=None):
    """logits [B,S,V] float32, targets [B,S] int32."""
    import jax
    import jax.numpy as jnp

    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, targets[..., None],
                               axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def init_train_state(params, optimizer, mesh=None, param_axes=None,
                     rules=None):
    """Build a TrainState; with a mesh + axes tree the params (and the
    optimizer state, which mirrors the param tree) are placed sharded."""
    if mesh is not None and param_axes is not None:
        params = shard_pytree(params, param_axes, mesh, rules)
    opt_state = optimizer.init(params)
    return TrainState(params, opt_state)


def make_train_step(loss_fn, optimizer, mesh=None, batch_axes=("batch",),
                    rules=None, donate: bool = True):
    """Compile a full train step.

    loss_fn(params, batch) -> scalar loss.  Returns
    step(state, batch) -> (state, loss), jitted; with a mesh, the batch is
    constrained onto the data axis and state donation keeps params
    in-place on device."""
    import jax

    def train_step(state, batch):
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, named_sharding(mesh, *batch_axes[:x.ndim],
                                      rules=rules)),
                batch)
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())
