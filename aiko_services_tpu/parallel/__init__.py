# Parallelism substrate: meshes, sharding rules, collectives, ring
# attention.  The TPU-native replacement for distribution the reference
# does over MQTT (SURVEY.md §2 "Parallelism & distribution components").
#
# jax is imported lazily inside functions — control-plane-only processes
# never pay for it.

from .mesh import (                                         # noqa: F401
    AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_SEQUENCE, AXIS_STAGE,
    MeshSpec, best_mesh_shape, create_mesh, single_device_mesh,
)
from .sharding import (                                     # noqa: F401
    DEFAULT_RULES, ShardingRules, constrain, named_sharding, replicated,
    shard_pytree,
)
from .collectives import (                                  # noqa: F401
    all_gather, axis_index, axis_size, device_transfer, pmax, pmean,
    ppermute_ring, psum, reduce_scatter, ring_neighbours,
)
from .ring_attention import (                               # noqa: F401
    attention_reference, ring_attention, ring_attention_sharded,
)
from .checkpoint import (                                   # noqa: F401
    CheckpointManager, restore_checkpoint, save_checkpoint,
)
from .pipeline_parallel import (                            # noqa: F401
    StagedExecutor, gpipe_spmd, stage_device_groups,
)
from .train import (                                        # noqa: F401
    TrainState, cross_entropy_loss, init_train_state, make_train_step,
)
