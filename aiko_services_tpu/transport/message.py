# Message transport interface: the control-plane seam.
#
# Capability parity with the reference Message ABC
# (reference: aiko_services/message/message.py:11-46): publish / subscribe /
# unsubscribe / last-will-and-testament.  Every implementation delivers
# inbound messages by calling `on_message(topic, payload)` — implementations
# may call it from any thread; the process runtime is responsible for
# marshalling onto its event engine.

from __future__ import annotations

from typing import Callable

__all__ = ["Message", "topic_matches"]


def _py_topic_matches(pattern: str, topic: str) -> bool:
    if pattern == topic:
        return True
    p_parts = pattern.split("/")
    t_parts = topic.split("/")
    for i, p in enumerate(p_parts):
        if p == "#":
            return True
        if i >= len(t_parts):
            return False
        if p != "+" and p != t_parts[i]:
            return False
    return len(p_parts) == len(t_parts)


def _select_topic_matches():
    try:
        from ..native import NATIVE_AVAILABLE, native_topic_matches
        if NATIVE_AVAILABLE:
            return native_topic_matches
    except Exception:
        pass
    return _py_topic_matches


_impl_topic_matches = None


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT-style topic match: '+' one level, '#' trailing multi-level.
    Native (C++) implementation when the toolchain built it; Python
    fallback otherwise (parity tested in tests/test_native.py)."""
    global _impl_topic_matches
    if _impl_topic_matches is None:
        _impl_topic_matches = _select_topic_matches()
    return _impl_topic_matches(pattern, topic)


class Message:
    """Abstract pub/sub transport.

    BINARY: True when the implementation carries bytes payloads end to
    end (the binary wire envelope, transport/wire.py, requires it);
    False means callers must fall back to S-expression text."""

    BINARY = False

    def __init__(self, on_message: Callable[[str, object], None] | None = None,
                 subscriptions=()):
        self.on_message = on_message
        self.subscriptions: set[str] = set(subscriptions)

    # -- lifecycle --------------------------------------------------------
    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        raise NotImplementedError

    def connected(self) -> bool:
        raise NotImplementedError

    # -- pub/sub ----------------------------------------------------------
    def publish(self, topic: str, payload, retain: bool = False,
                wait: bool = False) -> None:
        raise NotImplementedError

    def subscribe(self, topic: str) -> None:
        raise NotImplementedError

    def unsubscribe(self, topic: str) -> None:
        raise NotImplementedError

    def set_last_will_and_testament(
            self, topic: str, payload, retain: bool = False) -> None:
        raise NotImplementedError
