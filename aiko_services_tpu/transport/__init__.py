from .chaos import (                                        # noqa: F401
    ChaosBroker, ChaosMessage, FaultPlan, FaultRule,
)
from .message import Message, topic_matches                 # noqa: F401
from .memory import MemoryBroker, MemoryMessage, default_broker  # noqa: F401
from .mqtt import MQTT_AVAILABLE, MQTTMessage               # noqa: F401
from .peer import (                                         # noqa: F401
    ChaosPeerChannel, MemoryPeerChannel, PeerChannel, PeerHost,
    SocketPeerChannel,
)
from .wire import (                                         # noqa: F401
    WIRE_CODECS, WireError, contains_binary, decode_envelope,
    encode_envelope, encode_rpc, is_envelope, supports_binary,
)
