from .message import Message, topic_matches                 # noqa: F401
from .memory import MemoryBroker, MemoryMessage, default_broker  # noqa: F401
from .mqtt import MQTT_AVAILABLE, MQTTMessage               # noqa: F401
