# MQTT transport (optional): real-broker interop for multi-host control.
#
# Capability parity with the reference MQTT wrapper
# (reference: aiko_services/message/mqtt.py:64-284): connect with LWT,
# TLS/credentials, subscribe/unsubscribe, wait-for-publish — plus the
# robustness the reference lacks: automatic reconnect with exponential
# backoff, re-subscribe after reconnect, and bounded buffering of
# publishes made while disconnected (the reference busy-waits up to 2 s
# and drops, mqtt.py:250-284).
#
# Reconnect ownership: a real paho client reconnects ITSELF — its
# loop_start thread retries with reconnect_delay_set backoff, and racing
# a second reconnect() against it corrupts the socket state.  So with
# paho we configure its backoff and stand down; the timer-based
# machinery below drives reconnection only for injected clients (tests,
# alternative transports) that do not auto-reconnect.
#
# The underlying client is injectable (`client_factory`) so the
# machinery is testable without a live broker; the default factory
# builds a real paho client.  Gated on paho-mqtt being importable; the
# in-memory broker is the default transport so nothing in the framework
# requires it.

from __future__ import annotations

import random
import threading
from collections import deque

from .message import Message
from .wire import is_envelope
from ..utils import get_logger, jittered_backoff

__all__ = ["MQTT_AVAILABLE", "MQTTMessage"]

try:
    import paho.mqtt.client as _paho
    MQTT_AVAILABLE = True
except ImportError:        # pragma: no cover - environment without paho
    _paho = None
    MQTT_AVAILABLE = False

_BACKOFF_MIN = 0.5         # seconds; doubles per failed attempt
_BACKOFF_MAX = 30.0
_BACKOFF_JITTER = 0.25     # fraction of the delay added, seeded rng —
                           # a broker restart must not get every client
                           # redialing on the same doubling schedule
_BUFFER_LIMIT = 1024       # publishes held while disconnected

logger = get_logger("transport.mqtt")


def _paho_factory():       # pragma: no cover - needs paho installed
    if not MQTT_AVAILABLE:
        raise ImportError(
            "paho-mqtt is not installed; use the memory transport or "
            "install paho-mqtt for multi-host control planes")
    return _paho.Client(
        callback_api_version=_paho.CallbackAPIVersion.VERSION2)


def _is_failure(reason_code) -> bool:
    """True when a CONNACK reason code reports failure (paho v2 passes a
    ReasonCode object; fakes/v1 pass an int, 0 = success)."""
    if hasattr(reason_code, "is_failure"):
        return bool(reason_code.is_failure)
    return bool(reason_code)


class MQTTMessage(Message):
    """Message transport over an MQTT broker.

    The client object must expose the paho v2 surface used here:
    connect/reconnect/disconnect, loop_start/loop_stop, subscribe/
    unsubscribe, publish, will_set, and the on_connect/on_disconnect/
    on_message callback slots."""

    BINARY = True       # MQTT payloads are bytes; envelopes pass through

    def __init__(self, on_message=None, subscriptions=(),
                 host="localhost", port=1883, username=None, password=None,
                 tls=False, lwt_topic=None, lwt_payload=None,
                 lwt_retain=False, client_factory=None,
                 backoff_min=_BACKOFF_MIN, backoff_max=_BACKOFF_MAX,
                 backoff_jitter=_BACKOFF_JITTER, jitter_seed=None,
                 buffer_limit=_BUFFER_LIMIT):
        super().__init__(on_message, subscriptions)
        self.host, self.port = host, port
        self.backoff_min, self.backoff_max = backoff_min, backoff_max
        self.backoff_jitter = backoff_jitter
        # seeded so tests reproduce the exact delay sequence; None keeps
        # production spread (urandom-seeded)
        self._jitter_rng = random.Random(jitter_seed)
        self._attempts = 0          # consecutive reconnect attempts
        self._connected_event = threading.Event()
        self._closing = False
        self._lock = threading.RLock()
        self._pending = deque(maxlen=buffer_limit)   # (topic, payload, retain)
        self._reconnect_timer = None
        # counter increments mirror onto the metrics registry
        # (mqtt_client_events_total{kind=...}); last_error is a string
        # and stays dict-only
        from ..observe.metrics import MirroredStats
        self.stats = MirroredStats(
            {"reconnects": 0, "buffered": 0, "dropped": 0,
             "last_error": None},
            metric="mqtt_client_events_total",
            help="MQTT client lifecycle/buffering events by kind")

        self._client = (client_factory or _paho_factory)()
        # paho's network-loop thread auto-reconnects; give it our backoff
        # and let it own reconnection (see module docstring)
        self._client_reconnects = MQTT_AVAILABLE and \
            isinstance(self._client, _paho.Client)
        if self._client_reconnects:              # pragma: no cover - paho
            # paho takes integer seconds and requires min <= max
            min_delay = max(1, int(round(backoff_min)))
            self._client.reconnect_delay_set(
                min_delay=min_delay,
                max_delay=max(min_delay, int(round(backoff_max))))
        if username:
            self._client.username_pw_set(username, password)
        if tls:                                      # pragma: no cover
            self._client.tls_set()
        if lwt_topic is not None:
            self._client.will_set(lwt_topic, lwt_payload, retain=lwt_retain)
        self._client.on_connect = self._on_connect
        self._client.on_disconnect = self._on_disconnect
        self._client.on_message = self._on_paho_message

    # -- callbacks (broker/network thread) --------------------------------
    def _on_connect(self, client, userdata, flags, reason_code,
                    properties=None):
        if _is_failure(reason_code):
            # rejected CONNACK (bad credentials, not authorized, ...):
            # NOT a connection — the broker will close the socket
            self.stats["last_error"] = f"connect rejected: {reason_code}"
            logger.warning("MQTT connect rejected by %s:%s: %s",
                           self.host, self.port, reason_code)
            return
        # re-subscribe EVERY topic on EVERY (re)connect: broker-side
        # session state cannot be assumed (clean-session default)
        for topic in tuple(self.subscriptions):
            client.subscribe(topic)
        self._attempts = 0
        # drain the buffer BEFORE announcing connected: a concurrent
        # publish() seeing connected()=True must not overtake buffered
        # messages (retained last-write-wins topics would invert state)
        self._flush_pending()
        self._connected_event.set()
        self._flush_pending()       # anything buffered during the drain

    def _on_disconnect(self, client, userdata, flags, reason_code=None,
                       properties=None):
        self._connected_event.clear()
        if not self._closing and not self._client_reconnects:
            self._schedule_reconnect()

    def _on_paho_message(self, client, userdata, message):
        if self.on_message is not None:
            payload = message.payload
            if not is_envelope(payload):
                try:
                    payload = payload.decode("utf-8")
                except UnicodeDecodeError:
                    pass    # binary topic: hand bytes through
            self.on_message(message.topic, payload)

    # -- reconnect machinery (non-paho clients only) -----------------------
    def _schedule_reconnect(self) -> None:
        with self._lock:
            if self._closing or (self._reconnect_timer is not None
                                 and self._reconnect_timer.is_alive()):
                return
            # jittered exponential backoff (shared formula, utils/
            # backoff.py) so a fleet of clients fans out instead of
            # stampeding the broker together
            self._attempts += 1
            delay = jittered_backoff(
                self.backoff_min, self._attempts, self.backoff_max,
                self.backoff_jitter, self._jitter_rng)
            timer = threading.Timer(delay, self._attempt_reconnect)
            timer.daemon = True
            self._reconnect_timer = timer
            timer.start()

    def _attempt_reconnect(self) -> None:
        # the lock spans the closing-check AND the reconnect so a
        # concurrent disconnect() cannot interleave (reconnect-after-
        # shutdown); RLock + fakes calling _on_connect synchronously is
        # re-entrant-safe
        with self._lock:
            self._reconnect_timer = None
            if self._closing or self.connected():
                return
            self.stats["reconnects"] += 1
            try:
                self._client.reconnect()
            except Exception as exc:
                self.stats["last_error"] = repr(exc)
                logger.warning("MQTT reconnect to %s:%s failed (%r); "
                               "retrying in ~%.1fs",
                               self.host, self.port, exc,
                               min(self.backoff_min * (2 ** self._attempts),
                                   self.backoff_max))
                self._schedule_reconnect()    # next try, doubled backoff

    def _flush_pending(self) -> None:
        # serialized so two threads (on_connect network thread + a
        # publish() caller hitting the re-check) cannot interleave pops
        # and reorder the buffered messages.  Publishing under the lock
        # is deliberate here — paho's publish() only enqueues to its own
        # network thread, and releasing between pop and publish would
        # reopen the reorder window the lock exists to close.
        with self._lock:
            while self._pending:
                try:
                    topic, payload, retain = self._pending.popleft()
                except IndexError:        # pragma: no cover - race
                    break
                # graft: disable=lint-publish-locked (see comment above)
                self._client.publish(topic, payload, retain=retain)

    # -- Message interface -------------------------------------------------
    def connect(self, timeout=5.0) -> None:
        self._closing = False
        try:
            self._client.connect(self.host, self.port)
        except Exception as exc:
            self.stats["last_error"] = repr(exc)
            logger.warning("MQTT connect to %s:%s failed (%r)",
                           self.host, self.port, exc)
            self._client.loop_start()
            if not self._client_reconnects:
                self._schedule_reconnect()
            return
        self._client.loop_start()
        self._connected_event.wait(timeout)

    def disconnect(self) -> None:
        with self._lock:
            self._closing = True
            if self._reconnect_timer is not None:
                self._reconnect_timer.cancel()
                self._reconnect_timer = None
        self._client.loop_stop()
        self._client.disconnect()
        self._connected_event.clear()

    def crash(self) -> None:
        """Simulate abrupt process death (tests / chaos soaks): stop
        the reconnect machinery, then sever the link UNGRACEFULLY so
        the broker fires this client's LWT.  Loopback clients
        (transport/paho_loopback.py) expose drop() for the ungraceful
        cut; against a real paho client the socket is simply abandoned
        — the broker's keepalive generates the LWT."""
        with self._lock:
            self._closing = True
            if self._reconnect_timer is not None:
                self._reconnect_timer.cancel()
                self._reconnect_timer = None
        drop = getattr(self._client, "drop", None)
        if drop is not None:
            drop()
        else:                               # pragma: no cover — real paho
            self._client.loop_stop()
        self._connected_event.clear()

    def connected(self) -> bool:
        return self._connected_event.is_set()

    def wait_connected(self, timeout=5.0) -> bool:
        return self._connected_event.wait(timeout)

    def publish(self, topic, payload, retain=False, wait=False) -> None:
        if not self.connected():
            # wait=True means the caller needs delivery, not buffering
            # (e.g. presence marker before exit): give the reconnect a
            # bounded chance first
            if not (wait and self._connected_event.wait(2.0)):
                self.stats["buffered"] += 1
                if len(self._pending) == self._pending.maxlen:
                    self.stats["dropped"] += 1
                self._pending.append((topic, payload, retain))
                # a reconnect may have flushed between the check and the
                # append — drain again so the message cannot strand
                if self.connected():
                    self._flush_pending()
                return
        info = self._client.publish(topic, payload, retain=retain)
        if wait and hasattr(info, "wait_for_publish"):
            info.wait_for_publish(timeout=2.0)

    def subscribe(self, topic) -> None:
        self.subscriptions.add(topic)
        # always forward: if the resubscribe loop in _on_connect already
        # snapshotted (race), this call lands it; while disconnected paho
        # returns MQTT_ERR_NO_CONN without raising and the next
        # _on_connect replays from self.subscriptions
        try:
            self._client.subscribe(topic)
        except Exception:
            pass

    def unsubscribe(self, topic) -> None:
        self.subscriptions.discard(topic)
        try:
            self._client.unsubscribe(topic)
        except Exception:
            pass

    def set_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        """LWT can only change on (re)connect: cycle the connection if
        live (reference behavior: aiko_services/message/mqtt.py:187-196)."""
        self._client.will_set(topic, payload, retain=retain)
        if self.connected():
            # paho auto-reconnects only on UNEXPECTED drops; after a
            # requested disconnect we must redial explicitly
            self._client.disconnect()
            if self._client_reconnects:          # pragma: no cover - paho
                try:
                    self._client.reconnect()
                except Exception as exc:
                    self.stats["last_error"] = repr(exc)
