# MQTT transport (optional): real-broker interop for multi-host control.
#
# Capability parity with the reference MQTT wrapper
# (reference: aiko_services/message/mqtt.py:64-284): connect with LWT,
# TLS/credentials, subscribe/unsubscribe, wait-for-publish.  Gated on
# paho-mqtt being importable; the in-memory broker is the default transport
# so nothing in the framework requires paho.

from __future__ import annotations

import threading

from .message import Message

__all__ = ["MQTT_AVAILABLE", "MQTTMessage"]

try:
    import paho.mqtt.client as _paho
    MQTT_AVAILABLE = True
except ImportError:        # pragma: no cover - environment without paho
    _paho = None
    MQTT_AVAILABLE = False


class MQTTMessage(Message):   # pragma: no cover - needs a live broker
    def __init__(self, on_message=None, subscriptions=(),
                 host="localhost", port=1883, username=None, password=None,
                 tls=False, lwt_topic=None, lwt_payload=None,
                 lwt_retain=False):
        if not MQTT_AVAILABLE:
            raise ImportError(
                "paho-mqtt is not installed; use the memory transport or "
                "install paho-mqtt for multi-host control planes")
        super().__init__(on_message, subscriptions)
        self.host, self.port = host, port
        self._connected_event = threading.Event()
        self._client = _paho.Client(
            callback_api_version=_paho.CallbackAPIVersion.VERSION2)
        if username:
            self._client.username_pw_set(username, password)
        if tls:
            self._client.tls_set()
        if lwt_topic is not None:
            self._client.will_set(lwt_topic, lwt_payload, retain=lwt_retain)
        self._client.on_connect = self._on_connect
        self._client.on_disconnect = self._on_disconnect
        self._client.on_message = self._on_paho_message

    def _on_connect(self, client, userdata, flags, reason_code, properties):
        for topic in self.subscriptions:
            client.subscribe(topic)
        self._connected_event.set()

    def _on_disconnect(self, client, userdata, flags, reason_code,
                       properties):
        self._connected_event.clear()

    def _on_paho_message(self, client, userdata, message):
        if self.on_message is not None:
            payload = message.payload
            try:
                payload = payload.decode("utf-8")
            except UnicodeDecodeError:
                pass    # binary topic: hand bytes through
            self.on_message(message.topic, payload)

    def connect(self, timeout=5.0) -> None:
        self._client.connect(self.host, self.port)
        self._client.loop_start()
        self._connected_event.wait(timeout)

    def disconnect(self) -> None:
        self._client.loop_stop()
        self._client.disconnect()
        self._connected_event.clear()

    def connected(self) -> bool:
        return self._connected_event.is_set()

    def publish(self, topic, payload, retain=False, wait=False) -> None:
        info = self._client.publish(topic, payload, retain=retain)
        if wait:
            info.wait_for_publish(timeout=2.0)

    def subscribe(self, topic) -> None:
        self.subscriptions.add(topic)
        if self.connected():
            self._client.subscribe(topic)

    def unsubscribe(self, topic) -> None:
        self.subscriptions.discard(topic)
        if self.connected():
            self._client.unsubscribe(topic)

    def set_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        self._client.will_set(topic, payload, retain=retain)
