# Peer data plane: registrar-negotiated direct binary channels (ISSUE 6).
#
# BENCH_r05 put the number on the README's two-plane design: at 40
# sustained wire streams, 1182 ms of the 1359 ms p50 is wire overhead,
# and every tensor still funnels through a single broker hop.  This
# module takes the control-plane/data-plane split to its conclusion:
# the broker carries discovery, control, and the channel HANDSHAKE; bulk
# data-plane envelopes (transport/wire.py) move over direct peer
# channels negotiated through that control plane.
#
#   * PeerHost      — one per ProcessRuntime: advertises an endpoint in
#                     the service discovery record (tag "peer=..."),
#                     answers broker-mediated handshakes, owns the
#                     channel table and the topic→channel pin map the
#                     runtime's publish() consults;
#   * MemoryPeerChannel — same-process peers: envelopes hop straight
#                     from the sender into the receiver runtime's event
#                     queue (no broker lock, no routing, no per-client
#                     queues);
#   * SocketPeerChannel — same-host peers over a localhost/unix socket,
#                     cross-host peers over TCP: length-prefixed frames
#                     carrying (topic, payload), one reader thread per
#                     connection marshalling onto the event engine;
#   * ChaosPeerChannel — the chaos seam: a FaultPlan gets the same
#                     drop / delay / duplicate / truncate / partition
#                     control over peer channels it has over the broker
#                     (transport/chaos.py), applied on the SEND side.
#
# Negotiation (all over the broker, so it inherits its delivery
# guarantees and its chaos):
#
#   caller                                  serving
#   ------                                  -------
#   read "peer=kind:addr:nonce" tag from the discovery record
#   (peer_open hs_id reply_topic name
#              own_endpoint nonce kind
#              (reply_topics...))  ──────►  nonce == current?  no → refuse
#                                           accept_handler veto? → refuse
#                                           create/expect channel, pin
#                                           reply_topics → channel
#   pin data topics → channel     ◄──────  (peer_accept hs_id chan_id
#                                           kind name)
#
# The nonce is minted per PeerHost incarnation: a stale discovery record
# from a restarted process fails the handshake loudly instead of
# pinning frames to a corpse.  Duplicate accepts (chaos duplication,
# caller retries) dedup on the handshake id.
#
# Fallback ladder — peer, then broker: a refused handshake, a dead
# channel, or a failover simply leaves (or puts back) the broker path;
# the pipeline's recovery machinery (retry, candidate rotation,
# in-flight redirect, dedup/replay — ISSUE 4) and tracing/deadlines
# (ISSUE 5) ride either path unchanged because the envelope payload is
# byte-identical.  A channel death also schedules re-negotiation on the
# initiating side, so a transient kill degrades to the broker and then
# climbs back onto the direct path.

from __future__ import annotations

import itertools
import random
import socket
import struct
import threading
import uuid

from ..observe.metrics import MirroredStats, default_registry
from ..utils import Lock, get_logger, jittered_backoff
from .wire import is_envelope

__all__ = [
    "PeerHost", "PeerChannel", "MemoryPeerChannel", "SocketPeerChannel",
    "ChaosPeerChannel", "parse_endpoints", "PEER_TAG",
]

PEER_TAG = "peer"
_HANDSHAKE_TIMEOUT = 2.0        # seconds (engine clock)
_HANDSHAKE_ATTEMPTS = 3
_RENEGOTIATE_DELAY = 0.5        # base re-dial delay (doubles per redial)
_RENEGOTIATE_MAX_DELAY = 30.0
_MAX_REDIALS = 8                # then park the record on the cool-down
_GIVEUP_COOLDOWN = 60.0         # parked-record re-dial period: the
                                # registrar suppresses identical re-add
                                # events, so a caller that gave up must
                                # climb back on its OWN slow clock, not
                                # wait for a rediscovery that may never
                                # fire for an unchanged record
_ANSWERED_OPEN_CAP = 256        # served handshake ids kept for replay
_EXPECTED_HELLO_CAP = 64        # accepted-but-unconnected socket slots
_FRAME_HEAD = struct.Struct("<BIQ")     # is_text, topic_len, payload_len
_MAX_FRAME = 1 << 31            # sanity bound on one socket frame

logger = get_logger("transport.peer")

# Same-process endpoint table: token → PeerHost.  The "mem" flavor of a
# channel is just two hosts in one interpreter handing payloads to each
# other's event queues; this table is how a caller recognizes that the
# advertised endpoint lives in its own process.
_MEM_ENDPOINTS: dict[str, "PeerHost"] = {}
_channel_counter = itertools.count(1)


def parse_endpoints(tag_value: str) -> list[tuple]:
    """Parse a "peer" tag value into (kind, address, nonce) descriptors.

    Formats (joined by ","):  mem:<token>:<nonce>
                              uds:<path>:<nonce>
                              tcp:<host>:<port>:<nonce>
    """
    endpoints = []
    for desc in (tag_value or "").split(","):
        parts = desc.strip().split(":")
        if len(parts) < 3:
            continue
        kind = parts[0]
        if kind in ("mem", "uds"):
            endpoints.append((kind, ":".join(parts[1:-1]), parts[-1]))
        elif kind == "tcp" and len(parts) >= 4:
            try:
                port = int(parts[-2])
            except ValueError:
                # a malformed foreign tag must degrade to "no peer
                # endpoint", never raise into discovery handlers
                continue
            endpoints.append((kind, (":".join(parts[1:-2]), port),
                              parts[-1]))
    return endpoints


class PeerChannel:
    """One direct data-plane link.  send() returns False when the
    channel can no longer carry traffic — the caller falls back to the
    broker and the close path schedules re-negotiation."""

    kind = "?"

    def __init__(self, channel_id: str, peer_name: str = ""):
        self.channel_id = channel_id
        self.peer_name = peer_name      # remote runtime's name
        self.alive = True
        self.initiated = False          # True on the dialing side
        self.service_topic_path = None  # set on the dialing side
        self.sent = 0                   # per-channel counters (reports)
        self.received = 0
        self.close_reason = ""

    def send(self, topic: str, payload) -> bool:
        raise NotImplementedError

    def close(self, reason: str = "") -> None:
        raise NotImplementedError

    def info(self) -> dict:
        return {"kind": self.kind, "peer": self.peer_name,
                "alive": self.alive, "sent": self.sent,
                "received": self.received,
                "close_reason": self.close_reason}


class MemoryPeerChannel(PeerChannel):
    """Same-process channel: one shared pair of ends; send() enqueues
    straight into the remote runtime's event queue.  No broker lock, no
    subscription matching, no per-client queue — the entire per-message
    cost is one thread-safe queue append."""

    kind = "mem"

    def __init__(self, channel_id: str, host: "PeerHost", peer_name: str):
        super().__init__(channel_id, peer_name)
        self.host = host
        self.remote: "MemoryPeerChannel | None" = None   # other end

    @classmethod
    def pair(cls, channel_id: str, host_a: "PeerHost",
             host_b: "PeerHost") -> tuple:
        end_a = cls(channel_id, host_a, host_b.runtime.name)
        end_b = cls(channel_id, host_b, host_a.runtime.name)
        end_a.remote, end_b.remote = end_b, end_a
        return end_a, end_b

    def send(self, topic: str, payload) -> bool:
        remote = self.remote
        if not self.alive or remote is None or not remote.alive:
            return False
        self.sent += 1
        remote.received += 1
        remote.host._receive(topic, payload, remote)
        return True

    def close(self, reason: str = "") -> None:
        ends = [self, self.remote] if self.remote is not None else [self]
        for end in ends:
            if end.alive:
                end.alive = False
                end.close_reason = end.close_reason or reason
                end.host._channel_closed(end, reason)


class SocketPeerChannel(PeerChannel):
    """Localhost-unix-socket or TCP channel.  Frames are
    (is_text u8, topic_len u32, payload_len u64, topic, payload); a
    daemon reader thread per connection delivers inbound frames to the
    owning host, and a daemon WRITER thread drains a bounded outbound
    queue — send() never touches the socket, so a slow peer whose
    kernel buffer fills can never block the event loop (send keeps
    appending, the queue sheds its OLDEST frame past the cap, exactly
    the broker data plane's drop policy)."""

    TX_LIMIT = 1024             # outbound frames held for the writer

    def __init__(self, channel_id: str, host: "PeerHost", sock,
                 kind: str, peer_name: str = ""):
        super().__init__(channel_id, peer_name)
        self.kind = kind
        self.host = host
        self._sock = sock
        self._write_lock = Lock(f"peer.write.{channel_id}")
        from collections import deque
        self._tx: "deque" = deque()
        self._tx_ready = threading.Event()
        self.shed = 0           # outbound frames dropped at the cap

    def start_reader(self) -> None:
        for target, label in ((self._read_loop, "read"),
                              (self._write_loop, "write")):
            thread = threading.Thread(
                target=target, daemon=True,
                name=f"peer-{label}-{self.channel_id}")
            thread.start()

    # -- framing -----------------------------------------------------------
    @staticmethod
    def write_frame(sock, topic: str, payload) -> None:
        is_text = isinstance(payload, str)
        body = payload.encode("utf-8") if is_text else bytes(payload)
        topic_bytes = topic.encode("utf-8")
        sock.sendall(_FRAME_HEAD.pack(1 if is_text else 0,
                                      len(topic_bytes), len(body))
                     + topic_bytes + body)

    @staticmethod
    def read_exact(sock, count: int) -> bytes | None:
        chunks = []
        while count > 0:
            chunk = sock.recv(min(count, 1 << 20))
            if not chunk:
                return None
            chunks.append(chunk)
            count -= len(chunk)
        return b"".join(chunks)

    @classmethod
    def read_frame(cls, sock):
        head = cls.read_exact(sock, _FRAME_HEAD.size)
        if head is None:
            return None
        is_text, topic_len, payload_len = _FRAME_HEAD.unpack(head)
        if topic_len > _MAX_FRAME or payload_len > _MAX_FRAME:
            return None
        topic = cls.read_exact(sock, topic_len)
        body = cls.read_exact(sock, payload_len)
        if topic is None or body is None:
            return None
        return (topic.decode("utf-8"),
                body.decode("utf-8") if is_text else body)

    # -- channel interface -------------------------------------------------
    def send(self, topic: str, payload) -> bool:
        if not self.alive:
            return False
        with self._write_lock:
            if len(self._tx) >= self.TX_LIMIT:
                # streaming consumers want the freshest frame: shed the
                # stalest (hop retries/dedup recover request/response)
                self._tx.popleft()
                self.shed += 1
                self.host.stats["tx_shed"] += 1
            self._tx.append((topic, payload))
        self._tx_ready.set()
        self.sent += 1
        return True

    def _write_loop(self) -> None:
        while self.alive:
            self._tx_ready.wait(0.5)
            while True:
                with self._write_lock:
                    if not self._tx:
                        self._tx_ready.clear()
                        break
                    topic, payload = self._tx.popleft()
                try:
                    self.write_frame(self._sock, topic, payload)
                except OSError:
                    self.close("io-error")
                    return

    def _read_loop(self) -> None:
        while self.alive:
            try:
                frame = self.read_frame(self._sock)
            except (OSError, ValueError, UnicodeDecodeError):
                # a torn/corrupt frame poisons the whole stream (length
                # prefixes desync): treat it like a dead link — the
                # sender falls back to the broker and re-negotiates
                frame = None
            if frame is None:
                self.close("remote-closed")
                return
            self.received += 1
            self.host._receive(frame[0], frame[1], self)

    def close(self, reason: str = "") -> None:
        if not self.alive:
            return
        self.alive = False
        self.close_reason = reason
        self._tx_ready.set()            # wake the writer so it exits
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self.host._channel_closed(self, reason)


class ChaosPeerChannel(PeerChannel):
    """FaultPlan seam for peer channels: wraps any channel and consults
    the plan per send — the same drop / delay / duplicate / truncate /
    partition vocabulary ChaosBroker applies per broker delivery
    (publish-side semantics, like ChaosMessage).  kill() severs the
    link as if the transport died: the wrapped channel closes, both
    sides unpin, and the initiator re-negotiates."""

    def __init__(self, inner: PeerChannel, plan, engine=None):
        self.inner = inner      # before base init: the alive property
        super().__init__(inner.channel_id, inner.peer_name)
        self.kind = inner.kind
        self.plan = plan
        self.engine = engine
        self.local_name = getattr(getattr(inner, "host", None),
                                  "client_id", "") or ""

    # state proxies: the raw channel owns liveness and counters
    @property
    def alive(self):                    # type: ignore[override]
        return self.inner.alive

    @alive.setter
    def alive(self, value):
        self.inner.alive = value

    def _now(self) -> float:
        return self.engine.clock.now() if self.engine is not None else 0.0

    def send(self, topic: str, payload) -> bool:
        if not self.inner.alive:
            return False
        verdict = self.plan.decide(topic, self.local_name,
                                   self.inner.peer_name, payload,
                                   self._now())
        if verdict.drop:
            return True         # "sent" — and lost on the wire
        delivered = payload
        if verdict.truncate_to is not None and \
                isinstance(payload, (bytes, bytearray, memoryview)):
            delivered = bytes(payload)[:verdict.truncate_to]
        ok = True
        for _ in range(1 + verdict.copies):
            if (verdict.delay > 0.0 or verdict.reorder) and \
                    self.engine is not None:
                self.engine.add_oneshot_handler(
                    lambda d=delivered: self.inner.send(topic, d),
                    verdict.delay)
            else:
                ok = self.inner.send(topic, delivered) and ok
        return ok

    def kill(self, reason: str = "chaos-kill") -> None:
        self.inner.close(reason)

    def close(self, reason: str = "") -> None:
        self.inner.close(reason)

    def info(self) -> dict:
        return self.inner.info()


class PeerHost:
    """The per-runtime peer data plane.

    Enable with ProcessRuntime.enable_peer(); afterwards every service
    this runtime registers advertises the endpoint tag, publish()
    consults the pin map, and inbound handshakes are answered on
    {topic_path}/0/peer.  kinds selects the channel flavors offered:
    "mem" (same process, always cheap), "uds" (same host), "tcp"
    (cross-host) — a caller picks the closest flavor it can reach."""

    def __init__(self, runtime, kinds=("mem",), fault_plan=None,
                 tcp_host: str = "127.0.0.1", uds_dir: str | None = None,
                 accept_handler=None,
                 handshake_timeout: float = _HANDSHAKE_TIMEOUT,
                 handshake_attempts: int = _HANDSHAKE_ATTEMPTS,
                 renegotiate_delay: float = _RENEGOTIATE_DELAY,
                 data_queue_limit: int = 1024,
                 jitter_seed: int | None = None):
        self.runtime = runtime
        self.client_id = runtime.name
        self.nonce = uuid.uuid4().hex[:8]
        self.token = f"pr-{uuid.uuid4().hex[:10]}"
        self.fault_plan = fault_plan
        self.accept_handler = accept_handler    # (name, kind) -> ok|reason
        self.handshake_timeout = float(handshake_timeout)
        self.handshake_attempts = int(handshake_attempts)
        self.renegotiate_delay = float(renegotiate_delay)
        # the broker data plane bounds a slow consumer's queue and
        # sheds (PR 2); the peer path mirrors that: at most
        # data_queue_limit channel-delivered envelopes may sit
        # unprocessed in the receiver's engine queue before inbound
        # channel deliveries are shed (counted, never silent)
        self.data_queue_limit = int(data_queue_limit)
        self._rx_pending = 0
        # re-dial jitter: unseeded spreads a fleet's redials for real;
        # seed it (chaos soak does) for bit-reproducible runs
        self._jitter_rng = random.Random(jitter_seed)
        self.closed = False
        self._lock = Lock(f"peer.host.{runtime.name}")
        self._channels: dict[str, PeerChannel] = {}
        self._pins: dict[str, PeerChannel] = {}     # topic → channel
        self._pending: dict[str, dict] = {}         # handshake_id → state
        self._offered: dict[str, PeerChannel] = {}  # mem ends awaiting adopt
        self._expected_hellos: dict[str, dict] = {}  # socket channel ids
        # serving side: answered handshake ids → accept params, so a
        # duplicated/retried peer_open replays the SAME accept instead
        # of building a second channel (bounded ring)
        self._answered_opens: dict[str, list] = {}
        # reply-pin attachment over a SHARED channel (ISSUE 14
        # satellite, the PR 6 named seam): a second pipeline whose
        # requests already ride an existing channel asks the serving
        # side to pin ITS reply topic too, instead of silently taking
        # broker replies forever.  (channel_id, topic) -> "pending" |
        # "acked"; dropped with the channel, re-sent when a pending
        # ask expires unanswered.
        self._attached: dict[tuple, str] = {}
        self._attach_pending: dict[str, dict] = {}
        # service_topic_path → negotiation record (for re-dialing)
        self._negotiations: dict[str, dict] = {}
        self._listeners: list = []      # (kind, sock, addr)
        self._endpoints: list[str] = [f"mem:{self.token}:{self.nonce}"]
        _MEM_ENDPOINTS[self.token] = self
        if "uds" in kinds or "tcp" in kinds:
            self._start_listeners(kinds, tcp_host, uds_dir)
        self.topic_peer = f"{runtime.topic_path}/0/peer"
        runtime.add_message_handler(self._peer_handler, self.topic_peer)
        # aggregated across hosts (host names are unbounded — no label)
        self.stats = MirroredStats(
            {"sent": 0, "received": 0, "fallback": 0, "handshakes": 0,
             "accepted": 0, "refused": 0, "rejected_stale": 0,
             "dup_accepts": 0, "closed": 0, "renegotiations": 0,
             "expired_handshakes": 0, "rx_shed": 0, "tx_shed": 0,
             "attach_requests": 0, "attach_pins": 0, "attach_acks": 0},
            metric="peer_events_total",
            help="peer data-plane events by kind, all hosts")
        self._open_gauge = default_registry().gauge(
            "peer_channels_open", "currently-open peer channels")

    # -- advertisement -----------------------------------------------------
    @property
    def tag(self) -> str:
        """The discovery-record tag every service of this runtime
        advertises: peer=<desc>[,<desc>...]."""
        return f"{PEER_TAG}={','.join(self._endpoints)}"

    def _start_listeners(self, kinds, tcp_host, uds_dir) -> None:
        if "uds" in kinds and hasattr(socket, "AF_UNIX"):
            import os
            import tempfile
            if uds_dir:
                directory = uds_dir
            else:
                directory = tempfile.mkdtemp(prefix="aiko_peer_")
                self._own_uds_dir = directory   # removed in close()
            path = os.path.join(directory, f"{self.token}.sock")
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            listener.bind(path)
            listener.listen(64)
            self._listeners.append(("uds", listener, path))
            self._endpoints.append(f"uds:{path}:{self.nonce}")
        if "tcp" in kinds:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((tcp_host, 0))
            listener.listen(64)
            host, port = listener.getsockname()[:2]
            self._listeners.append(("tcp", listener, (host, port)))
            self._endpoints.append(f"tcp:{host}:{port}:{self.nonce}")
        for kind, listener, _ in self._listeners:
            thread = threading.Thread(
                target=self._accept_loop, args=(kind, listener),
                daemon=True, name=f"peer-accept-{self.token}")
            thread.start()

    # -- hot path ----------------------------------------------------------
    def maybe_send(self, topic: str, payload) -> bool:
        """Try the peer data plane for one outbound message.  Only
        binary envelopes ride channels — text RPCs and retained state
        stay on the broker (they ARE the control plane)."""
        channel = self._pins.get(topic)
        if channel is None or not is_envelope(payload):
            return False
        if channel.send(topic, payload):
            self.stats["sent"] += 1
            return True
        # dead channel: shed the pin and let the broker carry this one
        # (the close path has/will schedule re-negotiation)
        self.stats["fallback"] += 1
        channel.close(channel.close_reason or "send-failed")
        return False

    def _receive(self, topic: str, payload, channel) -> None:
        """Inbound from a channel (any thread): hand to the runtime's
        transport-inbound path, which marshals onto the event engine.
        Bounded: past data_queue_limit unprocessed deliveries the
        newest inbound envelope is shed — a stalled receiver must not
        accumulate channel traffic without bound (the broker path's
        bounded per-client queues, mirrored)."""
        with self._lock:
            if self._rx_pending >= self.data_queue_limit:
                shed = True
            else:
                shed = False
                self._rx_pending += 1
        if shed:
            self.stats["rx_shed"] += 1
            return
        self.stats["received"] += 1
        self.runtime._on_transport_message(topic, payload,
                                           ack=self._rx_drained)

    def _rx_drained(self) -> None:
        with self._lock:
            self._rx_pending = max(0, self._rx_pending - 1)

    # -- caller side -------------------------------------------------------
    def negotiate(self, service_topic_path: str, tag_value: str,
                  pin_topics, reply_topics, _redial: bool = False) -> bool:
        """Open (or re-open) a channel to the process serving
        `service_topic_path`, advertised as `tag_value`.  pin_topics are
        the topics THIS host will send to over the channel; the serving
        side pins reply_topics back to it.  Idempotent: an existing pin
        or an in-flight handshake for the same service is left alone.
        Returns True when a handshake was started."""
        if self.closed:
            return False
        with self._lock:
            # record the CURRENT facts first, even when already pinned
            # or mid-handshake: a later re-negotiation (channel death)
            # must dial the freshest advertised endpoint, not the tag
            # from the original negotiation (a restarted service whose
            # re-add beat its LWT remove would otherwise strand us on
            # a stale nonce forever)
            record = self._negotiations.setdefault(
                service_topic_path,
                {"service": service_topic_path, "attempts": 0})
            # topics ACCUMULATE across negotiators: two pipelines
            # sharing one service each contribute their reply topic,
            # and a redial after a channel death must re-pin BOTH —
            # overwriting with the latest caller's list silently
            # stranded the earlier pipeline's replies on the broker
            # after every redial (review finding)
            record.update({
                "tag": tag_value,
                "pin_topics": sorted(
                    set(record.get("pin_topics", ())) |
                    set(pin_topics)),
                "reply_topics": sorted(
                    set(record.get("reply_topics", ())) |
                    set(reply_topics))})
            if not _redial:
                # fresh EXTERNAL discovery facts earn a fresh retry/
                # redial budget (a service that once exhausted its
                # attempts must not keep a one-attempt budget forever);
                # internal re-dials keep their counters so the
                # escalation/cool-down ladder cannot be reset from
                # inside its own loop
                record["attempts"] = 0
                record["redials"] = 0
            pinned = next((self._pins[t] for t in pin_topics
                           if t in self._pins), None)
            missing: list = []
            if pinned is not None:
                # requests already ride a live channel: a SECOND
                # pipeline negotiating the same service only needs its
                # reply topics pinned on the serving side — attach
                # them over the existing channel instead of silently
                # leaving its replies on the broker (PR 6 named seam).
                # The send happens OUTSIDE the lock (it publishes).
                missing = [t for t in reply_topics if pinned.alive and
                           (pinned.channel_id, t) not in self._attached]
                for topic in missing:
                    self._attached[(pinned.channel_id, topic)] = \
                        "pending"
            elif any(p["service"] == service_topic_path
                     for p in self._pending.values()):
                return False
        if pinned is not None:
            if missing:
                self._send_attach(service_topic_path, pinned, missing)
            return False
        return self._dial(record)

    def _choose_endpoint(self, tag_value: str):
        """Closest reachable flavor wins: mem (same process) > uds
        (same host) > tcp."""
        endpoints = parse_endpoints(tag_value)
        for kind, address, nonce in endpoints:
            if kind == "mem" and address in _MEM_ENDPOINTS:
                return (kind, address, nonce)
        for preferred in ("uds", "tcp"):
            for kind, address, nonce in endpoints:
                if kind == preferred:
                    return (kind, address, nonce)
        return None

    def _dial(self, record: dict) -> bool:
        chosen = self._choose_endpoint(record.get("tag", ""))
        if chosen is None:
            return False
        kind, address, nonce = chosen
        handshake_id = uuid.uuid4().hex[:12]
        state = {"service": record["service"], "kind": kind,
                 "address": address, "nonce": nonce,
                 "pin_topics": record["pin_topics"],
                 "reply_topics": record["reply_topics"]}
        with self._lock:
            self._pending[handshake_id] = state
        state["timer"] = self.runtime.event.add_oneshot_handler(
            lambda: self._handshake_expired(handshake_id),
            self.handshake_timeout)
        self.stats["handshakes"] += 1
        from ..utils import generate
        from ..service import ServiceTopicPath
        parsed = ServiceTopicPath.parse(record["service"])
        process_path = parsed.process_path if parsed else record["service"]
        self.runtime.publish(
            f"{process_path}/0/peer",
            generate("peer_open",
                     [handshake_id, self.topic_peer, self.client_id,
                      ",".join(self._endpoints), nonce, kind,
                      list(record["reply_topics"])]))
        return True

    def _handshake_expired(self, handshake_id: str) -> None:
        with self._lock:
            state = self._pending.pop(handshake_id, None)
            # a mem end the serving side offered for this handshake is
            # now an orphan: close the pair so the serving side's
            # registered end (and its reply pin) is torn down too
            orphan = self._offered.pop(handshake_id, None)
        if orphan is not None:
            orphan.close("handshake-expired")
        if state is None:
            return
        self.stats["expired_handshakes"] += 1
        record = self._negotiations.get(state["service"])
        if record is None:
            return
        record["attempts"] += 1
        if record["attempts"] < self.handshake_attempts:
            self._dial(record)
        else:
            logger.warning(
                "peer %s: handshake with %s gave up after %d attempts; "
                "broker path until the cool-down re-dial",
                self.client_id, state["service"], record["attempts"])
            self._park_record(state["service"])

    # -- handshake protocol (broker messages) ------------------------------
    def _peer_handler(self, _topic, payload) -> None:
        from ..utils import parse
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "peer_open" and len(params) >= 7:
            self._on_peer_open(params)
        elif command == "peer_accept" and len(params) >= 4:
            self._on_peer_accept(params)
        elif command == "peer_refuse" and len(params) >= 2:
            self._on_peer_refuse(params)
        elif command == "peer_attach" and len(params) >= 5:
            self._on_peer_attach(params)
        elif command == "peer_attached" and len(params) >= 2:
            self._on_peer_attached(params)

    def _refuse(self, reply_topic, handshake_id, reason) -> None:
        from ..utils import generate
        self.stats["refused"] += 1
        self.runtime.publish(reply_topic,
                             generate("peer_refuse",
                                      [handshake_id, reason]))

    def _on_peer_open(self, params) -> None:
        handshake_id, reply_topic, caller_name, caller_endpoints, \
            nonce, kind = [str(p) for p in params[:6]]
        reply_topics = [str(t) for t in (params[6] or [])] \
            if isinstance(params[6], (list, tuple)) else [str(params[6])]
        if self.closed:
            return
        with self._lock:
            answered = self._answered_opens.get(handshake_id)
        if answered is not None:
            # duplicated (chaos) or retried peer_open: replay the SAME
            # accept — never build a second channel for one handshake
            from ..utils import generate
            self.runtime.publish(reply_topic,
                                 generate("peer_accept", answered))
            return
        if nonce != self.nonce:
            # a restarted incarnation minted a fresh nonce: the caller
            # is dialing a stale discovery record — refuse loudly so it
            # stays on the (correct) broker path until rediscovery
            self.stats["rejected_stale"] += 1
            self._refuse(reply_topic, handshake_id, "stale-nonce")
            return
        if self.accept_handler is not None:
            verdict = self.accept_handler(caller_name, kind)
            if verdict not in (True, None):
                self._refuse(reply_topic, handshake_id,
                             str(verdict) if verdict else "refused")
                return
        if kind == "mem":
            caller_host = None
            for ep_kind, address, _ in parse_endpoints(caller_endpoints):
                if ep_kind == "mem":
                    caller_host = _MEM_ENDPOINTS.get(address)
                    break
            if caller_host is None or caller_host.closed:
                self._refuse(reply_topic, handshake_id, "no-mem-endpoint")
                return
            channel_id = f"ch-{next(_channel_counter)}"
            ours, theirs = MemoryPeerChannel.pair(channel_id, self,
                                                 caller_host)
            self._register(ours, reply_topics)
            with caller_host._lock:
                caller_host._offered[handshake_id] = theirs
                # bound the adoption table: if offers pile up (accepts
                # all dropped AND expiry cleanup raced), the oldest
                # pair is torn down rather than leaked
                evicted = []
                while len(caller_host._offered) > _EXPECTED_HELLO_CAP:
                    evicted.append(caller_host._offered.pop(
                        next(iter(caller_host._offered))))
            for channel in evicted:
                channel.close("offer-evicted")
        elif kind in ("uds", "tcp"):
            channel_id = f"ch-{next(_channel_counter)}"
            with self._lock:
                self._expected_hellos[channel_id] = {
                    "reply_topics": reply_topics,
                    "peer_name": caller_name}
                # accepted-but-never-connected slots must not pile up
                # under a flaky dialer: oldest expectations expire
                while len(self._expected_hellos) > _EXPECTED_HELLO_CAP:
                    self._expected_hellos.pop(
                        next(iter(self._expected_hellos)))
        else:
            self._refuse(reply_topic, handshake_id,
                         f"unsupported-kind-{kind}")
            return
        from ..utils import generate
        accept = [handshake_id, channel_id, kind, self.client_id]
        with self._lock:
            self._answered_opens[handshake_id] = accept
            while len(self._answered_opens) > _ANSWERED_OPEN_CAP:
                self._answered_opens.pop(next(iter(self._answered_opens)))
        self.stats["accepted"] += 1
        self.runtime.publish(reply_topic, generate("peer_accept", accept))

    def _on_peer_accept(self, params) -> None:
        handshake_id, channel_id, kind, serving_name = \
            [str(p) for p in params[:4]]
        with self._lock:
            state = self._pending.pop(handshake_id, None)
            # an accept for a handshake we no longer await (chaos
            # duplicate, or OUR side expired it and re-dialed while the
            # open was in flight): any mem end offered under that id is
            # an orphan — close the pair so the serving side's
            # registered end and reply pin are torn down too
            orphan = None if state is not None \
                else self._offered.pop(handshake_id, None)
        if state is None:
            if orphan is not None:
                orphan.close("stale-handshake")
            self.stats["dup_accepts"] += 1
            return
        self._cancel_handshake_timer(state)
        if kind == "mem":
            with self._lock:
                channel = self._offered.pop(handshake_id, None)
            if channel is None:
                return
            channel.peer_name = serving_name
            channel.initiated = True
            channel.service_topic_path = state["service"]
            self._register(channel, state["pin_topics"])
            self._note_attached(channel.channel_id,
                                state["reply_topics"])
            record = self._negotiations.get(state["service"])
            if record is not None:      # a live channel earns a clean
                record["attempts"] = 0  # retry/redial budget back
                record["redials"] = 0
        else:
            # sockets: connect + hello off the event loop — a dial to a
            # dead host must not stall every pipeline in the process
            thread = threading.Thread(
                target=self._connect_socket,
                args=(state, channel_id, kind, serving_name), daemon=True,
                name=f"peer-dial-{channel_id}")
            thread.start()

    def _connect_socket(self, state, channel_id, kind,
                        serving_name) -> None:
        try:
            if kind == "uds":
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(state["address"])
            else:
                sock = socket.create_connection(state["address"],
                                                timeout=5.0)
                sock.settimeout(None)
            SocketPeerChannel.write_frame(
                sock, "", f"peer_hello {channel_id} {self.client_id}")
        except OSError as exc:
            logger.warning("peer %s: %s dial to %r failed: %r",
                           self.client_id, kind, state["address"], exc)
            record = self._negotiations.get(state["service"])
            if record is not None:
                self._schedule_renegotiation(state["service"])
            return
        channel = SocketPeerChannel(channel_id, self, sock, kind,
                                    peer_name=serving_name)
        channel.initiated = True
        channel.service_topic_path = state["service"]
        self._register(channel, state["pin_topics"])
        self._note_attached(channel_id, state["reply_topics"])
        channel.start_reader()
        record = self._negotiations.get(state["service"])
        if record is not None:
            record["attempts"] = 0
            record["redials"] = 0

    # -- reply-pin attachment over a shared channel (ISSUE 14 satellite) ----
    def _send_attach(self, service_topic_path: str, channel,
                     topics) -> None:
        """Ask the serving side of an existing channel to pin `topics`
        (our reply topics) to it.  Rides the broker like the handshake;
        an unanswered ask expires and a later negotiate retries."""
        attach_id = uuid.uuid4().hex[:12]
        state = {"channel_id": channel.channel_id,
                 "topics": list(topics)}
        with self._lock:
            self._attach_pending[attach_id] = state
            while len(self._attach_pending) > _EXPECTED_HELLO_CAP:
                self._expire_attach_locked(
                    next(iter(self._attach_pending)))
        state["timer"] = self.runtime.event.add_oneshot_handler(
            lambda: self._attach_expired(attach_id),
            self.handshake_timeout)
        self.stats["attach_requests"] += 1
        from ..utils import generate
        from ..service import ServiceTopicPath
        parsed = ServiceTopicPath.parse(service_topic_path)
        process_path = parsed.process_path if parsed \
            else service_topic_path
        self.runtime.publish(
            f"{process_path}/0/peer",
            generate("peer_attach",
                     [attach_id, self.topic_peer, channel.channel_id,
                      self.client_id, list(topics)]))

    def _expire_attach_locked(self, attach_id: str) -> None:
        state = self._attach_pending.pop(attach_id, None)
        if state is None:
            return
        for topic in state["topics"]:
            key = (state["channel_id"], topic)
            if self._attached.get(key) == "pending":
                del self._attached[key]     # a later negotiate retries

    def _attach_expired(self, attach_id: str) -> None:
        with self._lock:
            self._expire_attach_locked(attach_id)

    def _on_peer_attach(self, params) -> None:
        """Serving side: pin the caller's reply topics to an ALREADY
        open channel it shares with another pipeline of the same
        process — no new handshake, no second channel."""
        attach_id, reply_topic, channel_id, _caller = \
            [str(p) for p in params[:4]]
        topics = [str(t) for t in (params[4] or [])] \
            if isinstance(params[4], (list, tuple)) else [str(params[4])]
        if self.closed:
            return
        with self._lock:
            channel = self._channels.get(channel_id)
            if channel is not None and channel.alive:
                for topic in topics:
                    self._pins[topic] = channel
            else:
                channel = None
        if channel is None:
            self._refuse(reply_topic, attach_id, "no-channel")
            return
        self.stats["attach_pins"] += len(topics)
        logger.info("peer %s: attached %r to channel %s",
                    self.client_id, topics, channel_id)
        from ..utils import generate
        self.runtime.publish(reply_topic,
                             generate("peer_attached",
                                      [attach_id, channel_id]))

    def _on_peer_attached(self, params) -> None:
        attach_id = str(params[0])
        with self._lock:
            state = self._attach_pending.pop(attach_id, None)
            if state is not None:
                for topic in state["topics"]:
                    key = (state["channel_id"], topic)
                    if key in self._attached:
                        self._attached[key] = "acked"
        if state is None:
            return
        timer = state.get("timer")
        if timer is not None:
            self.runtime.event.remove_timer_handler(timer)
        self.stats["attach_acks"] += 1

    def _on_peer_refuse(self, params) -> None:
        handshake_id, reason = str(params[0]), str(params[1])
        attach_timer = None
        with self._lock:
            state = self._pending.pop(handshake_id, None)
            if state is None and handshake_id in self._attach_pending:
                # a refused ATTACH (channel died serving-side): clear
                # the pending marks so a later negotiate retries or
                # re-dials with current facts
                attach_timer = \
                    self._attach_pending[handshake_id].get("timer")
                self._expire_attach_locked(handshake_id)
        if attach_timer is not None:
            self.runtime.event.remove_timer_handler(attach_timer)
            return
        if state is None:
            return
        self._cancel_handshake_timer(state)
        logger.info("peer %s: handshake with %s refused (%s); "
                    "broker path stays", self.client_id,
                    state["service"], reason)
        # a stale-nonce refusal means our endpoint record is outdated:
        # drop the negotiation — rediscovery (a fresh registrar add with
        # the new tag) re-triggers negotiate() with current facts
        if reason == "stale-nonce":
            self._negotiations.pop(state["service"], None)

    def _cancel_handshake_timer(self, state) -> None:
        timer = state.get("timer")
        if timer is not None:
            self.runtime.event.remove_timer_handler(timer)
            state["timer"] = None

    # -- socket listener side ----------------------------------------------
    def _accept_loop(self, kind, listener) -> None:
        while not self.closed:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            thread = threading.Thread(
                target=self._serve_connection, args=(kind, sock),
                daemon=True, name=f"peer-conn-{self.token}")
            thread.start()

    def _serve_connection(self, kind, sock) -> None:
        try:
            frame = SocketPeerChannel.read_frame(sock)
        except (OSError, ValueError, UnicodeDecodeError):
            # stray connections (port scanners, misdirected clients)
            # send arbitrary bytes: reject and close, never let the
            # accept path die with a leaked fd
            frame = None
        if frame is None:
            sock.close()
            return
        parts = str(frame[1] if isinstance(frame[1], str)
                    else frame[1].decode("utf-8", "replace")).split()
        if len(parts) != 3 or parts[0] != "peer_hello":
            sock.close()
            return
        channel_id, peer_name = parts[1], parts[2]
        with self._lock:
            expected = self._expected_hellos.pop(channel_id, None)
        if expected is None:
            sock.close()
            return
        channel = SocketPeerChannel(channel_id, self, sock, kind,
                                    peer_name=peer_name)
        self._register(channel, expected["reply_topics"])
        channel.start_reader()

    # -- channel table -----------------------------------------------------
    def _wrap(self, channel: PeerChannel) -> PeerChannel:
        if self.fault_plan is None:
            return channel
        wrapper = ChaosPeerChannel(channel, self.fault_plan,
                                   engine=self.runtime.event)
        wrapper.local_name = self.client_id
        return wrapper

    def _register(self, channel: PeerChannel, topics) -> None:
        wrapped = self._wrap(channel)
        with self._lock:
            self._channels[channel.channel_id] = wrapped
            for topic in topics:
                self._pins[topic] = wrapped
        self._open_gauge.inc()
        logger.info("peer %s: %s channel %s to %s pinned for %r",
                    self.client_id, channel.kind, channel.channel_id,
                    channel.peer_name, list(topics))

    def _note_attached(self, channel_id: str, topics) -> None:
        """Record reply topics the serving side pinned as part of the
        ORIGINAL negotiation, so a later negotiate over the shared
        channel only attaches genuinely new ones."""
        with self._lock:
            for topic in topics or ():
                self._attached[(channel_id, topic)] = "acked"

    def _channel_closed(self, channel: PeerChannel, reason: str) -> None:
        with self._lock:
            registered = self._channels.pop(channel.channel_id, None)
            if registered is None:
                return
            dead_topics = [t for t, c in self._pins.items()
                           if c.channel_id == channel.channel_id]
            for topic in dead_topics:
                del self._pins[topic]
            for key in [k for k in self._attached
                        if k[0] == channel.channel_id]:
                del self._attached[key]
        self.stats["closed"] += 1
        self._open_gauge.dec()
        service = self._channel_service(channel) or \
            self._channel_service(registered)
        if not self.closed and reason not in ("released", "shutdown") \
                and service is not None:
            self._schedule_renegotiation(service)

    @staticmethod
    def _channel_service(channel):
        """The dialed service a channel belongs to — set on the RAW
        channel, so look through a ChaosPeerChannel wrapper too."""
        if channel is None:
            return None
        service = getattr(channel, "service_topic_path", None)
        if service is None:
            service = getattr(getattr(channel, "inner", None),
                              "service_topic_path", None)
        return service

    def _schedule_renegotiation(self, service_topic_path: str) -> None:
        """A dead dialed channel climbs back: after a (growing) delay
        the negotiation record re-dials — fresh handshake, fresh nonce
        check — while traffic keeps flowing over the broker.  Redials
        back off exponentially and are CAPPED: a persistently
        unreachable endpoint (accepted handshake, unconnectable socket)
        drops the record after _MAX_REDIALS, and only a fresh discovery
        event (new registrar add with current facts) starts over."""
        record = self._negotiations.get(service_topic_path)
        if record is None or self.closed:
            return
        record["attempts"] = 0              # fresh handshake budget
        record["redials"] = record.get("redials", 0) + 1
        if record["redials"] > _MAX_REDIALS:
            logger.warning(
                "peer %s: channel to %s keeps dying (%d redials); "
                "broker path until the cool-down re-dial",
                self.client_id, service_topic_path, _MAX_REDIALS)
            self._park_record(service_topic_path)
            return
        # the shared fleet-safe backoff formula (utils/backoff.py): a
        # restarted serving killing N callers' channels at once must
        # not get N re-dials in lockstep every round
        delay = jittered_backoff(self.renegotiate_delay,
                                 record["redials"],
                                 _RENEGOTIATE_MAX_DELAY, 0.25,
                                 self._jitter_rng)
        self.stats["renegotiations"] += 1
        self.runtime.event.add_oneshot_handler(
            lambda: self._renegotiate(service_topic_path), delay)

    def _park_record(self, service_topic_path: str) -> None:
        """Handshake/redial budget exhausted: keep the negotiation
        record but only re-dial on a slow cool-down clock.  Rediscovery
        cannot be relied on to restart us — the registrar suppresses
        identical re-add events — so the climb-back is self-driven."""
        record = self._negotiations.get(service_topic_path)
        if record is None or self.closed:
            return
        record["attempts"] = 0
        record["redials"] = 0
        delay = _GIVEUP_COOLDOWN * \
            (1.0 + 0.25 * self._jitter_rng.random())
        self.runtime.event.add_oneshot_handler(
            lambda: self._renegotiate(service_topic_path), delay)

    def _renegotiate(self, service_topic_path: str) -> None:
        record = self._negotiations.get(service_topic_path)
        if record is None or self.closed:
            return
        self.negotiate(service_topic_path, record.get("tag", ""),
                       record.get("pin_topics", ()),
                       record.get("reply_topics", ()), _redial=True)

    def unregister_reply_topic(self, topic: str) -> None:
        """Remove `topic` from every negotiation record's accumulated
        reply list (and its attach marks): a per-instance reply topic
        (e.g. a disagg client's uuid-suffixed one) whose owner is gone
        must not be re-pinned forever on every redial — the
        accumulation fix would otherwise leak one dead topic per
        client incarnation (review finding).  Serving-side pins of the
        dead topic die with the channel."""
        with self._lock:
            for record in self._negotiations.values():
                topics = record.get("reply_topics")
                if topics and topic in topics:
                    record["reply_topics"] = [t for t in topics
                                              if t != topic]
            for key in [k for k in self._attached if k[1] == topic]:
                del self._attached[key]

    def release(self, topic: str, close_channel: bool = True) -> None:
        """Drop the pin for `topic` (service left, pipeline stopped).
        The channel closes once nothing is pinned to it."""
        with self._lock:
            channel = self._pins.pop(topic, None)
            if channel is None:
                return
            still_pinned = any(c.channel_id == channel.channel_id
                               for c in self._pins.values())
        service = self._channel_service(channel)
        if service is not None:
            self._negotiations.pop(service, None)
        if close_channel and not still_pinned:
            channel.close("released")

    def kill_channels(self, reason: str = "chaos-kill") -> int:
        """Sever every open channel (chaos scenarios: the mid-stream
        link kill).  Traffic degrades to the broker; initiating sides
        re-negotiate after renegotiate_delay."""
        with self._lock:
            channels = list(self._channels.values())
        for channel in channels:
            channel.close(reason)
        return len(channels)

    # -- reporting ---------------------------------------------------------
    def info(self) -> dict:
        with self._lock:
            return {
                "endpoints": list(self._endpoints),
                "pins": {t: c.channel_id for t, c in self._pins.items()},
                "channels": {cid: c.info()
                             for cid, c in self._channels.items()},
                "stats": dict(self.stats),
            }

    def pinned(self, topic: str) -> bool:
        channel = self._pins.get(topic)
        return channel is not None and channel.alive

    # -- teardown ----------------------------------------------------------
    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        with self._lock:
            channels = list(self._channels.values())
            pending = list(self._pending.values())
            offered = list(self._offered.values())
            self._pending.clear()
            self._offered.clear()
            self._negotiations.clear()
        for state in pending:
            self._cancel_handshake_timer(state)
        for channel in channels + offered:
            channel.close("shutdown")
        for kind, listener, address in self._listeners:
            try:
                listener.close()
            except OSError:
                pass
            if kind == "uds":
                import contextlib
                import os
                with contextlib.suppress(OSError):
                    os.unlink(address)
        if getattr(self, "_own_uds_dir", None):
            import shutil
            shutil.rmtree(self._own_uds_dir, ignore_errors=True)
        _MEM_ENDPOINTS.pop(self.token, None)
        self.runtime.remove_message_handler(self._peer_handler,
                                            self.topic_peer)
