# Chaos transport: seeded, scriptable fault injection for the data plane.
#
# The reference framework's whole pitch is surviving a hostile distributed
# environment (LWT + retained messages for registrar failover, leases
# everywhere), yet neither it nor the seed of this repo could *inject* a
# fault to prove any of it.  This module is the deterministic chaos seam:
#
#   * FaultRule / FaultPlan — a schedule of per-topic / per-client faults
#     (drop, delay, duplicate, reorder, payload truncation) plus network
#     partitions, deterministic under a seed: a single random.Random
#     consumed in delivery order, so the same plan + the same engine
#     stepping reproduces the same fault sequence bit-for-bit;
#   * ChaosBroker — a MemoryBroker whose per-recipient delivery seam
#     (`_deliver`) consults the plan.  Drop it in wherever a MemoryBroker
#     goes (conftest `broker`, ProcessRuntime transport factories) and an
#     entire multi-runtime system runs under scheduled failure inside one
#     pytest;
#   * ChaosMessage — the same plan applied at the client edge of ANY
#     Message transport (publish-side), for brokers this process does not
#     own (a real mosquitto, an injected test transport).
#
# Fault semantics per delivery (one message, one recipient):
#   drop       message never reaches this recipient;
#   delay      message enqueued after `delay` seconds of engine time;
#   duplicate  recipient sees the message `copies + 1` times;
#   reorder    message held for one engine turn, so later messages in the
#              same burst overtake it (deterministic local reordering);
#   truncate   bytes payloads cut to `truncate_to` bytes — exercises the
#              wire-envelope decode error paths;
#   partition  clients are assigned to named groups; while a partition
#              window is active, messages do not cross group boundaries.
#
# Rules match MQTT-style topic patterns, fnmatch client ids (recipient
# AND sender), an optional payload substring (e.g. target only the
# "(primary absent)" LWT), count windows (`after`, `count`) and clock
# windows (`start`, `stop` in engine time).  Everything is observable:
# per-rule fired counts and a plan-wide stats Counter, so a soak can
# report exactly what it injected.

from __future__ import annotations

import random
from dataclasses import dataclass, field
from fnmatch import fnmatchcase

from ..observe import flight as _flight
from ..observe.metrics import MirroredStats
from .memory import MemoryBroker
from .message import Message, topic_matches

__all__ = ["FaultRule", "FaultPlan", "ChaosBroker", "ChaosMessage",
           "FAULT_KINDS"]

FAULT_KINDS = ("drop", "delay", "duplicate", "reorder", "truncate")


@dataclass
class FaultRule:
    """One scheduled fault.  See the module docstring for semantics."""
    kind: str
    topic: str = "#"                # MQTT pattern the topic must match
    client: str = "*"               # fnmatch on the RECIPIENT client id
    sender: str = "*"               # fnmatch on the SENDER client id
    probability: float = 1.0        # per matching delivery (seeded rng)
    delay: float = 0.05             # seconds, kind="delay"
    copies: int = 1                 # extra deliveries, kind="duplicate"
    truncate_to: int = 8            # bytes kept, kind="truncate"
    payload_match: str | None = None  # substring the payload must contain
    after: int = 0                  # skip the first N matching deliveries
    count: int | None = None        # fire at most N times
    start: float | None = None      # active window in engine-clock time
    stop: float | None = None
    seen: int = field(default=0, compare=False)    # matching deliveries
    fired: int = field(default=0, compare=False)   # faults applied

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")

    def _payload_contains(self, payload) -> bool:
        if self.payload_match is None:
            return True
        needle = self.payload_match
        if isinstance(payload, (bytes, bytearray, memoryview)):
            return needle.encode("utf-8") in bytes(payload)
        return needle in str(payload)

    def matches(self, topic, sender_id, recipient_id, payload, now) -> bool:
        if self.start is not None and now < self.start:
            return False
        if self.stop is not None and now >= self.stop:
            return False
        if not topic_matches(self.topic, topic):
            return False
        if not fnmatchcase(recipient_id or "", self.client):
            return False
        if not fnmatchcase(sender_id or "", self.sender):
            return False
        return self._payload_contains(payload)


@dataclass
class _Partition:
    groups: list                    # list of lists of client-id patterns
    start: float | None = None
    stop: float | None = None

    def active(self, now: float) -> bool:
        return (self.start is None or now >= self.start) and \
            (self.stop is None or now < self.stop)

    def group_of(self, client_id: str) -> int | None:
        for index, patterns in enumerate(self.groups):
            if any(fnmatchcase(client_id or "", p) for p in patterns):
                return index
        return None

    def severs(self, sender_id: str, recipient_id: str) -> bool:
        sender_group = self.group_of(sender_id)
        recipient_group = self.group_of(recipient_id)
        # unassigned clients (the registrar, observers) see everyone
        if sender_group is None or recipient_group is None:
            return False
        return sender_group != recipient_group


class _Verdict:
    """The composed decision for one (message, recipient) delivery."""
    __slots__ = ("drop", "delay", "copies", "truncate_to", "reorder")

    def __init__(self):
        self.drop = False
        self.delay = 0.0
        self.copies = 0
        self.truncate_to: int | None = None
        self.reorder = False


class FaultPlan:
    """A seeded schedule of faults.  Thread-compatible with the memory
    broker (decisions happen on the delivery path, outside the broker
    lock, which the engine serializes in deterministic tests)."""

    def __init__(self, seed: int = 0, rules=()):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = list(rules)
        self.partitions: list[_Partition] = []
        # Counter-compatible (missing keys read 0); injected-fault
        # counts also mirror onto the metrics registry, so a soak's
        # telemetry snapshot shows chaos_faults_total beside the
        # recovery counters it provoked
        self.stats = MirroredStats(
            metric="chaos_faults_total",
            help="chaos faults injected by kind")

    # -- authoring ---------------------------------------------------------
    def add(self, rule: FaultRule) -> FaultRule:
        self.rules.append(rule)
        return rule

    def drop(self, **kwargs) -> FaultRule:
        return self.add(FaultRule("drop", **kwargs))

    def delay(self, **kwargs) -> FaultRule:
        return self.add(FaultRule("delay", **kwargs))

    def duplicate(self, **kwargs) -> FaultRule:
        return self.add(FaultRule("duplicate", **kwargs))

    def reorder(self, **kwargs) -> FaultRule:
        return self.add(FaultRule("reorder", **kwargs))

    def truncate(self, **kwargs) -> FaultRule:
        return self.add(FaultRule("truncate", **kwargs))

    def partition(self, groups, start: float | None = None,
                  stop: float | None = None) -> "_Partition":
        """Sever the network between client groups for [start, stop) in
        engine-clock time.  `groups` is a list of lists of client-id
        fnmatch patterns; clients matching no group are unaffected."""
        partition = _Partition([list(g) for g in groups], start, stop)
        self.partitions.append(partition)
        return partition

    def clear(self) -> None:
        self.rules.clear()
        self.partitions.clear()

    # -- decision ----------------------------------------------------------
    def decide(self, topic, sender_id, recipient_id, payload,
               now: float) -> _Verdict:
        verdict = _Verdict()
        for partition in self.partitions:
            if partition.active(now) and \
                    partition.severs(sender_id, recipient_id):
                verdict.drop = True
                self.stats["partitioned"] += 1
                # flight-recorder evidence (ISSUE 11): every injected
                # fault lands in the per-runtime rings, so an SLO-breach
                # dump carries the faults that caused it — a no-op
                # when no recorder is registered
                _flight.record_fault("partitioned", topic, sender_id,
                                     recipient_id, now)
                return verdict
        for rule in self.rules:
            if not rule.matches(topic, sender_id, recipient_id, payload,
                                now):
                continue
            rule.seen += 1
            if rule.seen <= rule.after:
                continue
            if rule.count is not None and rule.fired >= rule.count:
                continue
            # one rng draw per probabilistic rule evaluation, in rule
            # order: the fault sequence is a pure function of (seed,
            # delivery order)
            if rule.probability < 1.0 and \
                    self.rng.random() >= rule.probability:
                continue
            rule.fired += 1
            self.stats[rule.kind] += 1
            _flight.record_fault(rule.kind, topic, sender_id,
                                 recipient_id, now)
            if rule.kind == "drop":
                verdict.drop = True
                return verdict
            if rule.kind == "delay":
                verdict.delay = max(verdict.delay, rule.delay)
            elif rule.kind == "duplicate":
                verdict.copies += rule.copies
            elif rule.kind == "reorder":
                verdict.reorder = True
            elif rule.kind == "truncate":
                verdict.truncate_to = rule.truncate_to
        return verdict

    def injected(self) -> int:
        """Total faults applied so far (all kinds + partition drops)."""
        return sum(self.stats.values())


def _apply_truncate(payload, nbytes: int):
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return bytes(payload)[:nbytes]
    return str(payload)[:nbytes]


class ChaosBroker(MemoryBroker):
    """A MemoryBroker that routes every delivery through a FaultPlan.

    `engine` provides the clock for rule windows and the timer wheel for
    delayed/reordered deliveries; without one, delay and reorder degrade
    to immediate delivery (drop/duplicate/truncate/partition still
    apply, with now=0.0 for window checks)."""

    def __init__(self, plan: FaultPlan | None = None, engine=None,
                 data_queue_limit: int = 1024):
        super().__init__(data_queue_limit)
        self.plan = plan or FaultPlan()
        self.engine = engine

    def _now(self) -> float:
        return self.engine.clock.now() if self.engine is not None else 0.0

    def _deliver(self, clients, topic, payload, is_data, sender) -> None:
        sender_id = getattr(sender, "client_id", "") or ""
        now = self._now()
        for client in clients:
            recipient_id = getattr(client, "client_id", "") or ""
            verdict = self.plan.decide(topic, sender_id, recipient_id,
                                       payload, now)
            if verdict.drop:
                continue
            delivered = payload if verdict.truncate_to is None else \
                _apply_truncate(payload, verdict.truncate_to)

            def enqueue(client=client, delivered=delivered):
                client._enqueue(topic, delivered, is_data,
                                self.data_queue_limit, self.stats)

            for _ in range(1 + verdict.copies):
                if verdict.delay > 0.0 and self.engine is not None:
                    self.engine.add_oneshot_handler(enqueue, verdict.delay)
                elif verdict.reorder and self.engine is not None:
                    # one-turn hold: later messages in this burst overtake
                    self.engine.add_oneshot_handler(enqueue, 0.0)
                else:
                    enqueue()


class ChaosMessage(Message):
    """Client-edge chaos for transports whose broker this process does
    not own: wraps any Message and applies the plan on the PUBLISH side
    (sender faults only — the wrapped transport's broker fans out, so
    per-recipient rules cannot apply here; use ChaosBroker for those)."""

    def __init__(self, inner: Message, plan: FaultPlan, engine=None,
                 client_id: str | None = None):
        super().__init__(inner.on_message, inner.subscriptions)
        self.inner = inner
        self.plan = plan
        self.engine = engine
        self.client_id = client_id or \
            getattr(inner, "client_id", "") or "chaos"
        self.BINARY = getattr(inner, "BINARY", False)

    def _now(self) -> float:
        return self.engine.clock.now() if self.engine is not None else 0.0

    def publish(self, topic, payload, retain=False, wait=False) -> None:
        verdict = self.plan.decide(topic, self.client_id, "*", payload,
                                   self._now())
        if verdict.drop:
            return
        delivered = payload if verdict.truncate_to is None else \
            _apply_truncate(payload, verdict.truncate_to)

        def send():
            self.inner.publish(topic, delivered, retain=retain, wait=wait)

        for _ in range(1 + verdict.copies):
            if (verdict.delay > 0.0 or verdict.reorder) and \
                    self.engine is not None:
                self.engine.add_oneshot_handler(send, verdict.delay)
            else:
                send()

    # -- passthrough -------------------------------------------------------
    def connect(self) -> None:
        self.inner.connect()

    def crash(self) -> None:
        """Abrupt-death passthrough (the soak kills a runtime through
        its transport, chaos wrapper or not)."""
        crash = getattr(self.inner, "crash", None)
        if crash is not None:
            crash()
        else:
            self.inner.disconnect()

    def disconnect(self, *args, **kwargs) -> None:
        self.inner.disconnect(*args, **kwargs)

    def connected(self) -> bool:
        return self.inner.connected()

    def subscribe(self, topic) -> None:
        self.subscriptions.add(topic)
        self.inner.subscribe(topic)

    def unsubscribe(self, topic) -> None:
        self.subscriptions.discard(topic)
        self.inner.unsubscribe(topic)

    def set_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        self.inner.set_last_will_and_testament(topic, payload, retain)
