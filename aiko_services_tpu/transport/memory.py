# In-memory broker: full pub/sub semantics without a network.
#
# The reference has no test transport (its only impl is paho-mqtt,
# aiko_services/message/mqtt.py:64); this broker is the designed-in seam the
# survey calls for (SURVEY.md §4): retained messages, +/# wildcards, and
# last-will-and-testament, so an entire multi-"process" distributed system —
# registrar failover included — runs deterministically inside one pytest.

from __future__ import annotations

import threading
from typing import Callable

from .message import Message, topic_matches

__all__ = ["MemoryBroker", "MemoryMessage"]


class MemoryBroker:
    """A process-local mosquitto: routes, retains, and fires LWTs."""

    def __init__(self):
        self._lock = threading.RLock()
        self._clients: list[MemoryMessage] = []
        self._retained: dict[str, object] = {}

    # -- client management -------------------------------------------------
    def attach(self, client: "MemoryMessage") -> None:
        with self._lock:
            if client not in self._clients:
                self._clients.append(client)

    def detach(self, client: "MemoryMessage", fire_lwt: bool = True) -> None:
        with self._lock:
            if client in self._clients:
                self._clients.remove(client)
        if fire_lwt:
            for topic, payload, retain in list(client.wills):
                self.route(topic, payload, retain=retain)

    # -- routing -----------------------------------------------------------
    def route(self, topic: str, payload, retain: bool = False) -> None:
        if retain:
            with self._lock:
                if payload in ("", b"", None):
                    self._retained.pop(topic, None)   # clear retained
                else:
                    self._retained[topic] = payload
        with self._lock:
            clients = list(self._clients)
        for client in clients:
            client._deliver(topic, payload)

    def deliver_retained(self, client: "MemoryMessage",
                         pattern: str) -> None:
        with self._lock:
            matches = [(t, p) for t, p in self._retained.items()
                       if topic_matches(pattern, t)]
        for topic, payload in matches:
            client._deliver(topic, payload)

    def retained(self, topic: str):
        with self._lock:
            return self._retained.get(topic)

    def reset(self) -> None:
        with self._lock:
            self._clients.clear()
            self._retained.clear()


_default_broker = MemoryBroker()


def default_broker() -> MemoryBroker:
    return _default_broker


class MemoryMessage(Message):
    """Message transport backed by a MemoryBroker."""

    def __init__(self, on_message: Callable | None = None, subscriptions=(),
                 broker: MemoryBroker | None = None,
                 lwt_topic: str | None = None, lwt_payload=None,
                 lwt_retain: bool = False):
        super().__init__(on_message, subscriptions)
        self.broker = broker or _default_broker
        self.wills: list[tuple[str, object, bool]] = []
        if lwt_topic is not None:
            self.wills.append((lwt_topic, lwt_payload, lwt_retain))
        self._connected = False
        # delivery index: exact topics hash-match in O(1); only
        # wildcard patterns scan.  A process with N services holds N+
        # subscriptions, and a linear topic_matches scan per inbound
        # message is O(N²) for an N-consumer fan-out — the reference's
        # documented scale bottleneck (its lifecycle.py:18-24).
        self._exact: set[str] = set()
        self._wild: list[str] = []
        for pattern in self.subscriptions:
            self._index(pattern)

    def _index(self, pattern: str) -> None:
        if "+" in pattern or "#" in pattern:
            if pattern not in self._wild:
                self._wild.append(pattern)
        else:
            self._exact.add(pattern)

    # -- lifecycle ---------------------------------------------------------
    def connect(self) -> None:
        self.broker.attach(self)
        self._connected = True
        for pattern in list(self.subscriptions):
            self.broker.deliver_retained(self, pattern)

    def disconnect(self, fire_lwt: bool = False) -> None:
        """Graceful disconnect does not fire the LWT (like MQTT DISCONNECT);
        pass fire_lwt=True to simulate a crash / broken session."""
        self.broker.detach(self, fire_lwt=fire_lwt)
        self._connected = False

    def crash(self) -> None:
        """Simulate abrupt process death: broker fires the LWT."""
        self.disconnect(fire_lwt=True)

    def connected(self) -> bool:
        return self._connected

    # -- pub/sub -----------------------------------------------------------
    def publish(self, topic, payload, retain=False, wait=False) -> None:
        self.broker.route(topic, payload, retain)

    def subscribe(self, topic) -> None:
        new = topic not in self.subscriptions
        self.subscriptions.add(topic)
        self._index(topic)
        if self._connected and new:
            self.broker.deliver_retained(self, topic)

    def unsubscribe(self, topic) -> None:
        self.subscriptions.discard(topic)
        self._exact.discard(topic)
        if topic in self._wild:
            self._wild.remove(topic)

    def set_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        self.wills = [(topic, payload, retain)]

    def add_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        """Additional will (real MQTT allows one will per connection; a
        registrar over MQTT uses a dedicated connection for this)."""
        self.wills.append((topic, payload, retain))

    def remove_last_will_and_testament(self, topic) -> None:
        self.wills = [w for w in self.wills if w[0] != topic]

    # -- delivery ----------------------------------------------------------
    def _deliver(self, topic: str, payload) -> None:
        if not self._connected or self.on_message is None:
            return
        if topic in self._exact:
            self.on_message(topic, payload)
            return
        for pattern in self._wild:
            if topic_matches(pattern, topic):
                self.on_message(topic, payload)
                return
