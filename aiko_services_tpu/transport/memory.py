# In-memory broker: full pub/sub semantics without a network.
#
# The reference has no test transport (its only impl is paho-mqtt,
# aiko_services/message/mqtt.py:64); this broker is the designed-in seam the
# survey calls for (SURVEY.md §4): retained messages, +/# wildcards, and
# last-will-and-testament, so an entire multi-"process" distributed system —
# registrar failover included — runs deterministically inside one pytest.
#
# Routing is INDEXED (ISSUE 2): the original route() scanned every attached
# client and matched every subscription pattern per message under one lock —
# O(clients x patterns) per publish, the reference's documented scale
# bottleneck (its lifecycle.py:18-24).  Now exact-topic subscriptions
# hash-match in O(1) through a topic map, wildcard patterns walk a
# per-level subscription trie, and delivery happens OUTSIDE the broker
# lock through per-client FIFO queues.  Data-plane topics (opt-in via
# mark_data_plane) get BOUNDED per-client queues with an explicit drop
# policy — a slow consumer sheds its own stale frames instead of
# back-pressuring the broker, and control-plane messages are never
# dropped.

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Callable

from ..observe.metrics import MirroredStats
from ..utils.lock import Lock
from .message import Message, topic_matches

__all__ = ["MemoryBroker", "MemoryMessage"]


class _TrieNode:
    """One topic level of the wildcard-subscription trie."""
    __slots__ = ("children", "plus", "multi", "leaf")

    def __init__(self):
        self.children: dict[str, _TrieNode] = {}
        self.plus: _TrieNode | None = None      # '+' single-level branch
        self.multi: set = set()                 # clients with '#' here
        self.leaf: set = set()                  # patterns ending here

    def empty(self) -> bool:
        return not (self.children or self.plus or self.multi or self.leaf)


class _SubscriptionTrie:
    """MQTT wildcard patterns ('+' one level, trailing '#') -> clients."""

    def __init__(self):
        self._root = _TrieNode()

    def insert(self, pattern: str, client) -> None:
        node = self._root
        for part in pattern.split("/"):
            if part == "#":
                node.multi.add(client)
                return
            if part == "+":
                if node.plus is None:
                    node.plus = _TrieNode()
                node = node.plus
            else:
                node = node.children.setdefault(part, _TrieNode())
        node.leaf.add(client)

    def remove(self, pattern: str, client) -> None:
        path = []                       # (parent, key) trail for pruning
        node = self._root
        for part in pattern.split("/"):
            if part == "#":
                node.multi.discard(client)
                break
            if part == "+":
                if node.plus is None:
                    return
                path.append((node, "+"))
                node = node.plus
            else:
                child = node.children.get(part)
                if child is None:
                    return
                path.append((node, part))
                node = child
        else:
            node.leaf.discard(client)
        while path and node.empty():
            parent, key = path.pop()
            if key == "+":
                parent.plus = None
            else:
                del parent.children[key]
            node = parent

    def match(self, topic: str) -> set:
        out: set = set()
        nodes = [self._root]
        for part in topic.split("/"):
            next_nodes = []
            for node in nodes:
                out |= node.multi           # "a/#" matches "a/b/..."
                child = node.children.get(part)
                if child is not None:
                    next_nodes.append(child)
                if node.plus is not None:
                    next_nodes.append(node.plus)
            nodes = next_nodes
            if not nodes:
                return out
        for node in nodes:
            out |= node.leaf
            out |= node.multi               # MQTT: "a/#" matches "a" too
        return out


class MemoryBroker:
    """A process-local mosquitto: routes, retains, and fires LWTs.

    data_queue_limit bounds each client's pending DATA-plane messages
    (topics registered via mark_data_plane); control-plane queues are
    unbounded so protocol messages can never be shed."""

    def __init__(self, data_queue_limit: int = 1024):
        self._lock = threading.RLock()
        self._clients: dict["MemoryMessage", int] = {}   # client -> seq
        self._seq = itertools.count()
        self._exact: dict[str, set] = {}
        self._trie = _SubscriptionTrie()
        self._retained: dict[str, object] = {}
        self._data_patterns: list[str] = []
        self.data_queue_limit = data_queue_limit
        # best-effort counters: delivered/dropped increment outside the
        # broker lock (per-client paths), so concurrent publishers may
        # lose the odd count — they are diagnostics, not invariants.
        # Mirrored onto the process metrics registry (ISSUE 5):
        # broker_messages_total{kind=...} aggregates across every
        # broker instance in the process
        self.stats = MirroredStats(
            {"routed": 0, "delivered": 0, "dropped": 0},
            metric="broker_messages_total",
            help="in-memory broker routing events by kind")

    # -- client management -------------------------------------------------
    def attach(self, client: "MemoryMessage") -> None:
        with self._lock:
            if client not in self._clients:
                self._clients[client] = next(self._seq)
                for pattern in client.subscriptions:
                    self._index(client, pattern)

    def detach(self, client: "MemoryMessage", fire_lwt: bool = True) -> None:
        with self._lock:
            if client in self._clients:
                del self._clients[client]
                for pattern in client.subscriptions:
                    self._unindex(client, pattern)
        if fire_lwt:
            for topic, payload, retain in list(client.wills):
                # the dying client is the logical sender of its own will
                self.route(topic, payload, retain=retain, sender=client)

    # -- subscription index (lock held by callers below) -------------------
    def _index(self, client, pattern: str) -> None:
        if "+" in pattern or "#" in pattern:
            self._trie.insert(pattern, client)
        else:
            self._exact.setdefault(pattern, set()).add(client)

    def _unindex(self, client, pattern: str) -> None:
        if "+" in pattern or "#" in pattern:
            self._trie.remove(pattern, client)
        else:
            subscribers = self._exact.get(pattern)
            if subscribers is not None:
                subscribers.discard(client)
                if not subscribers:
                    del self._exact[pattern]

    def subscribe(self, client: "MemoryMessage", pattern: str) -> None:
        with self._lock:
            if client in self._clients:
                self._index(client, pattern)

    def unsubscribe(self, client: "MemoryMessage", pattern: str) -> None:
        with self._lock:
            if client in self._clients:
                self._unindex(client, pattern)

    # -- data-plane policy -------------------------------------------------
    def mark_data_plane(self, pattern: str) -> None:
        """Topics matching `pattern` are data plane: a slow consumer's
        pending queue is bounded (data_queue_limit) and overflow is shed
        per the client's drop_policy instead of growing without bound."""
        with self._lock:
            if pattern not in self._data_patterns:
                self._data_patterns.append(pattern)

    def _is_data_topic(self, topic: str) -> bool:
        return any(topic_matches(p, topic) for p in self._data_patterns)

    # -- routing -----------------------------------------------------------
    def route(self, topic: str, payload, retain: bool = False,
              sender=None) -> None:
        with self._lock:
            if retain:
                if payload in ("", b"", None):
                    self._retained.pop(topic, None)   # clear retained
                else:
                    self._retained[topic] = payload
            recipients = self._exact.get(topic, set()) | \
                self._trie.match(topic)
            # deterministic fan-out order: attach order, like the old
            # linear scan delivered
            ordered = sorted(((self._clients[c], c) for c in recipients
                              if c in self._clients))
            is_data = bool(self._data_patterns) and \
                self._is_data_topic(topic)
            self.stats["routed"] += 1
        # delivery OUTSIDE the lock: a handler that publishes (actors
        # routinely do) re-enters route() without deadlock risk, and a
        # slow handler no longer serializes every other publisher
        self._deliver([client for _, client in ordered], topic, payload,
                      is_data, sender)

    def _deliver(self, clients, topic: str, payload, is_data: bool,
                 sender) -> None:
        """Per-recipient delivery, outside the broker lock.  The seam the
        chaos layer (transport/chaos.py) overrides to inject per-delivery
        faults; `sender` is the publishing client (None for retained
        replays), so partition rules can tell sides apart."""
        for client in clients:
            client._enqueue(topic, payload, is_data,
                            self.data_queue_limit, self.stats)

    def deliver_retained(self, client: "MemoryMessage",
                         pattern: str) -> None:
        with self._lock:
            matches = [(t, p) for t, p in self._retained.items()
                       if topic_matches(pattern, t)]
            data_flags = [bool(self._data_patterns) and
                          self._is_data_topic(t) for t, _ in matches]
        # retained replays go through the same per-recipient delivery
        # seam as live messages (sender=None), so chaos rules apply to
        # them too — a "dropped retained announcement" is testable
        for (topic, payload), is_data in zip(matches, data_flags):
            self._deliver([client], topic, payload, is_data, None)

    def retained(self, topic: str):
        with self._lock:
            return self._retained.get(topic)

    def reset(self) -> None:
        with self._lock:
            self._clients.clear()
            self._exact.clear()
            self._trie = _SubscriptionTrie()
            self._retained.clear()
            self._data_patterns.clear()


_default_broker = MemoryBroker()
_client_counter = itertools.count()


def default_broker() -> MemoryBroker:
    return _default_broker


class MemoryMessage(Message):
    """Message transport backed by a MemoryBroker.

    Inbound messages flow through a per-client FIFO queue drained outside
    the broker lock; drop_policy ("oldest" | "newest") applies only to
    data-plane topics when the queue is at the broker's bound."""

    BINARY = True       # bytes payloads (wire.py envelopes) pass through

    def __init__(self, on_message: Callable | None = None, subscriptions=(),
                 broker: MemoryBroker | None = None,
                 lwt_topic: str | None = None, lwt_payload=None,
                 lwt_retain: bool = False, drop_policy: str = "oldest",
                 client_id: str | None = None):
        super().__init__(on_message, subscriptions)
        self.broker = broker or _default_broker
        # identity for per-client fault rules (transport/chaos.py); the
        # LWT topic is the natural default — it names the owning process
        self.client_id = client_id or lwt_topic or \
            f"memory-{next(_client_counter)}"
        self.wills: list[tuple[str, object, bool]] = []
        if lwt_topic is not None:
            self.wills.append((lwt_topic, lwt_payload, lwt_retain))
        self._connected = False
        self.drop_policy = drop_policy
        # per-client dict; the registry mirror aggregates across
        # clients (no per-client label: client ids are unbounded)
        self.stats = MirroredStats(
            {"received": 0, "dropped": 0},
            metric="transport_client_messages_total",
            help="per-client transport deliveries/sheds, aggregated")
        # two FIFO lanes with a shared sequence so the drain preserves
        # global arrival order: the data lane is the bounded one, and
        # shedding is O(1) (popleft), never a scan
        self._rx_ctl: deque = deque()       # (seq, topic, payload)
        self._rx_data: deque = deque()
        self._rx_seq = itertools.count()
        self._rx_lock = Lock("memory.rx")
        self._draining = False
        self._held = False

    # -- lifecycle ---------------------------------------------------------
    def connect(self) -> None:
        self.broker.attach(self)
        self._connected = True
        for pattern in list(self.subscriptions):
            self.broker.deliver_retained(self, pattern)

    def disconnect(self, fire_lwt: bool = False) -> None:
        """Graceful disconnect does not fire the LWT (like MQTT DISCONNECT);
        pass fire_lwt=True to simulate a crash / broken session."""
        self.broker.detach(self, fire_lwt=fire_lwt)
        self._connected = False

    def crash(self) -> None:
        """Simulate abrupt process death: broker fires the LWT."""
        self.disconnect(fire_lwt=True)

    def connected(self) -> bool:
        return self._connected

    # -- pub/sub -----------------------------------------------------------
    def publish(self, topic, payload, retain=False, wait=False) -> None:
        self.broker.route(topic, payload, retain, sender=self)

    def subscribe(self, topic) -> None:
        new = topic not in self.subscriptions
        self.subscriptions.add(topic)
        if new:
            self.broker.subscribe(self, topic)
        if self._connected and new:
            self.broker.deliver_retained(self, topic)

    def unsubscribe(self, topic) -> None:
        if topic in self.subscriptions:
            self.subscriptions.discard(topic)
            self.broker.unsubscribe(self, topic)

    def mark_data_plane(self, pattern) -> None:
        """Declare a data-plane topic pattern on the backing broker
        (bounded per-client queues + drop policy; see MemoryBroker)."""
        self.broker.mark_data_plane(pattern)

    def set_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        self.wills = [(topic, payload, retain)]

    def add_last_will_and_testament(self, topic, payload,
                                    retain=False) -> None:
        """Additional will (real MQTT allows one will per connection; a
        registrar over MQTT uses a dedicated connection for this)."""
        self.wills.append((topic, payload, retain))

    def remove_last_will_and_testament(self, topic) -> None:
        self.wills = [w for w in self.wills if w[0] != topic]

    # -- delivery ----------------------------------------------------------
    def hold(self) -> None:
        """Pause delivery: inbound messages queue (tests exercise the
        bounded-queue drop policy with this)."""
        self._held = True

    def release(self) -> None:
        self._held = False
        self._pump()

    def _enqueue(self, topic: str, payload, is_data: bool,
                 limit: int, broker_stats: dict) -> None:
        if not self._connected:
            return
        with self._rx_lock:
            if is_data and limit and len(self._rx_data) >= limit:
                if self.drop_policy == "newest":
                    self.stats["dropped"] += 1
                    broker_stats["dropped"] += 1
                    return
                # "oldest" (default): shed the stalest data frame —
                # streaming consumers want the freshest payload
                self._rx_data.popleft()
                self.stats["dropped"] += 1
                broker_stats["dropped"] += 1
            lane = self._rx_data if is_data else self._rx_ctl
            lane.append((next(self._rx_seq), topic, payload))
        self._pump()

    def _pump(self) -> None:
        """Drain both rx lanes in global FIFO (sequence) order.
        Re-entrancy safe: a handler that publishes back to this client
        appends and returns — the outer drain delivers it, preserving
        order without unbounded recursion."""
        while True:
            with self._rx_lock:
                if self._draining or self._held or \
                        not (self._rx_ctl or self._rx_data):
                    return
                self._draining = True
            try:
                while True:
                    with self._rx_lock:
                        if self._held:
                            break
                        if self._rx_ctl and (
                                not self._rx_data or
                                self._rx_ctl[0][0] < self._rx_data[0][0]):
                            _, topic, payload = self._rx_ctl.popleft()
                        elif self._rx_data:
                            _, topic, payload = self._rx_data.popleft()
                        else:
                            break
                    if self._connected and self.on_message is not None:
                        self.stats["received"] += 1
                        self.broker.stats["delivered"] += 1
                        self.on_message(topic, payload)
            finally:
                with self._rx_lock:
                    self._draining = False
