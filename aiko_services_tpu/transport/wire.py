# Binary wire envelope: the zero-copy data-plane payload encoding.
#
# The control plane speaks S-expression text (utils/sexpr.py) — right for
# commands, wrong for tensors: BENCH_r05 measured the full wire pipeline
# at 40 real-time ASR streams with ~1 s of pure wire overhead per frame,
# most of it spent round-tripping ndarray payloads through text.  This
# module adds a length-prefixed binary envelope:
#
#   AIKW | version u8 | header_len u32 | header sexpr (utf-8)
#        | buffer_count u32 | (buffer_len u64, raw bytes) * count
#
# The header is an ordinary RPC S-expression "(command param...)" in which
# every ndarray / bytes value has been replaced by a marker list
# ["__aikb__", index, kind, dtype, dims, codec, meta]; the raw bytes ride
# out-of-band after the header.  Decoding reconstructs each ndarray as a
# read-only np.frombuffer VIEW over the received payload — tensors never
# round-trip through text and are never copied on the receive path.
# Encoding pays exactly one copy (the final b"".join); contiguous array
# bytes are taken as memoryviews, not .tobytes() copies.
#
# Codec tags plug the existing wire codecs in (opt-in, per-key):
#   "mulaw" — ops/audio.py µ-law companding: float audio ships as uint8
#             codes (half of int16, quarter of f32);
#   "i8"    — generic absmax int8: any float array ships quantized with
#             one f32 scale in the tag (mel features, activations);
#   "i8mel" — log-mel int8 with one scale PER MEL FRAME packed into the
#             buffer ([T, M+4] int8): the ASR wire codec — 3.8x fewer
#             bytes than f32 mel without letting a loud frame crush a
#             quiet one (ops/audio.py mel_i8_pack);
#   "dct8"  — ops/image_wire.py blockwise DCT: uint8 camera frames ship
#             as truncated int8 coefficients (4x fewer bytes at keep=16).
# A consumer that wants the DEVICE to expand a codec (the fused-frontend
# pattern) should ship pre-encoded codes as a plain uint8/int8 array
# instead — the envelope moves them untouched.
#
# Everything that is not an ndarray/bytes keeps S-expression semantics:
# scalars arrive as strings, exactly like the text path, so existing RPC
# consumers need no changes.  The sexpr path remains the fallback for
# non-binary-capable transports and for control-plane messages
# (encode_rpc below picks per payload).

from __future__ import annotations

import struct

import numpy as np

from ..observe.tracing import TRACE_MARKER
from ..utils.sexpr import generate, generate_sexpr, parse_sexpr

__all__ = [
    "MAGIC", "WIRE_VERSION", "WireError", "is_envelope", "contains_binary",
    "encode_envelope", "decode_envelope", "encode_rpc", "supports_binary",
    "WIRE_CODECS", "WIRE_CODEC_DTYPES", "WIRE_CODEC_RANK", "codec_legal",
    "pop_trace", "TENANT_MARKER", "tenant_fields", "is_tenant_fields",
    "parse_tenant", "pop_tenant", "KV_TRANSFER_COMMAND",
    "KV_BATCH_COMMAND", "encode_kv_batch", "decode_kv_batch",
    "kv_batch_members", "validate_kv_transfer_params",
    "KV_TRANSFER_SCHEMA", "KV_TRANSFER_DTYPES", "KV_TRANSFER_RANK",
    "kv_leaf_legal", "encode_kv_transfer", "decode_kv_transfer",
    "BUFFER_MARKER_ARITY", "TRACE_FIELDS_ARITY", "TENANT_FIELDS_ARITY",
    "HOP_ENTRY_FIELDS", "HOP_ENTRY_OPTIONAL", "KV_TRANSFER_PARAMS",
    "BUFFER_MARKER", "KV_MIGRATE_COMMAND", "KV_MIGRATE_ACK_COMMAND",
    "KV_MIGRATE_DONE_COMMAND", "KV_MIGRATE_PARAMS",
    "encode_kv_migrate", "validate_kv_migrate_params",
    "encode_kv_migrate_reply", "validate_kv_migrate_reply",
]

MAGIC = b"AIKW"
WIRE_VERSION = 1
_MARKER = BUFFER_MARKER = "__aikb__"
# Trace-context header marker (ISSUE 5): a trailing parameter
# ["__aikt__", trace_id, span_id, remaining, sent] rides in the
# envelope header (or appended to the sexpr params on text transports)
# and is stripped back out on decode — existing RPC consumers never see
# it.  The canonical constant lives in observe/tracing.py (which has no
# transport dependency, so the import cannot cycle).
_TRACE = TRACE_MARKER
# Tenant header marker (ISSUE 9): a trailing parameter
# ["__aikn__", tenant, tier] rides AFTER the trace marker in the
# envelope header (or appended to sexpr params on text transports) and
# is stripped back out on decode — the serving-side admission gate
# (ops/admission.py) charges the frame to the right per-tenant budget,
# existing RPC consumers never see it.
TENANT_MARKER = "__aikn__"
# Declared envelope arities and field lists — the wire-schema lock
# (analysis/wire_schema.lock, checked by graft-check's lint-wire-schema
# via analysis/drift.py) snapshots these, so any envelope change is an
# explicit two-sided diff: edit the constant AND regenerate the lock.
BUFFER_MARKER_ARITY = 7    # [tag, index, kind, dtype, dims, codec, meta]
TRACE_FIELDS_ARITY = 5     # [tag, trace_id, span_id, remaining, sent]
TENANT_FIELDS_ARITY = 3    # [tag, tenant, tier]
# one pipeline request hop as it crosses the peer wire (pipeline.py
# _hop_entry builds it; process_frames_remote consumes positionally)
HOP_ENTRY_FIELDS = ("stream_id", "inputs", "reply_topic", "hop_id")
HOP_ENTRY_OPTIONAL = ("trace", "tenant")
KV_TRANSFER_PARAMS = 8     # required param count; optional 9th "chunk"
_HEAD = struct.Struct("<BI")            # version, header_len
_COUNT = struct.Struct("<I")
_BUFLEN = struct.Struct("<Q")


class WireError(ValueError):
    """Raised when a payload is not a well-formed binary envelope."""


def supports_binary(transport) -> bool:
    """True when `transport` can carry bytes payloads end to end
    (Message implementations declare it with a BINARY class attr)."""
    return bool(getattr(transport, "BINARY", False))


def is_envelope(payload) -> bool:
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return bytes(payload[:4]) == MAGIC
    return False


def contains_binary(obj) -> bool:
    """True when obj (recursively) holds an ndarray or bytes value —
    the test for whether the sexpr text path could even express it."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return True
    if not isinstance(obj, (str, int, float, bool, type(None))) \
            and _is_arraylike(obj):
        return True
    if isinstance(obj, dict):
        return any(contains_binary(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(contains_binary(v) for v in obj)
    return False


def _is_arraylike(obj) -> bool:
    if isinstance(obj, np.ndarray):
        return True
    # jax.Array (and anything numpy-convertible that isn't a scalar)
    return hasattr(obj, "shape") and hasattr(obj, "dtype")


# -- codecs ------------------------------------------------------------------
# Each codec: encode(np.ndarray) -> (coded np.ndarray, meta list[str]);
#             decode(np.ndarray, meta) -> np.ndarray (the original value,
#             up to the codec's documented loss).

def _mulaw_encode(array):
    from ..ops.audio import mulaw_encode
    return mulaw_encode(array), [str(array.dtype)]


def _mulaw_decode(codes, meta):
    # numpy inverse of ops.audio.mulaw_decode (host-side: the transport
    # must not touch the accelerator)
    from ..ops.audio import MULAW_MU
    x = codes.astype(np.float32) * (1.0 / 127.5) - 1.0
    audio = np.sign(x) * np.expm1(
        np.abs(x) * np.log1p(MULAW_MU)) * (1.0 / MULAW_MU)
    return audio.astype(meta[0] if meta else np.float32)


def _i8_encode(array):
    # scale from FINITE values only: one inf/NaN glitch sample must not
    # poison the whole tensor (inf scale -> all-NaN decode); non-finite
    # entries saturate (inf) or zero (NaN) instead
    x = array.astype(np.float32)
    finite = x[np.isfinite(x)]
    scale = float(np.max(np.abs(finite))) / 127.0 if finite.size else 0.0
    scale = scale if scale and np.isfinite(scale) else 1.0
    bound = 127.0 * scale
    x = np.nan_to_num(x, nan=0.0, posinf=bound, neginf=-bound)
    q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
    return q, [str(array.dtype), repr(scale)]


def _i8_decode(q, meta):
    dtype, scale = meta[0], float(meta[1])
    return (q.astype(np.float32) * scale).astype(dtype)


def _i8mel_encode(array):
    # per-ROW absmax int8 (one f32 scale per mel frame, packed into the
    # trailing 4 bytes of each row): each 10 ms slice quantizes against
    # its own dynamic range — see ops/audio.py mel_i8_pack
    from ..ops.audio import mel_i8_pack
    return mel_i8_pack(array), [str(array.dtype)]


def _i8mel_decode(packed, meta):
    from ..ops.audio import mel_i8_unpack
    return mel_i8_unpack(packed).astype(meta[0] if meta else np.float32)


def _dct8_encode(array):
    from ..ops.image_wire import dct8_encode
    h, w, _ = array.shape
    return dct8_encode(array), [str(array.dtype), str(h), str(w)]


def _dct8_decode(codes, meta):
    # numpy inverse of ops.image_wire.dct8_decode (same math, host-side)
    from ..ops.image_wire import _DCT, _QUANT, _ZIGZAG
    dtype, height, width = meta[0], int(meta[1]), int(meta[2])
    hb, wb, channels, keep = codes.shape
    flat = np.zeros((hb, wb, channels, 64), np.float32)
    flat[..., _ZIGZAG[:keep]] = codes.astype(np.float32)
    coeffs = flat.reshape(hb, wb, channels, 8, 8) * _QUANT
    blocks = np.einsum("ik,whckl,jl->whcij", _DCT.T, coeffs, _DCT.T,
                       optimize=True)
    image = (blocks + 128.0).transpose(0, 3, 1, 4, 2).reshape(
        height, width, channels)
    return np.clip(np.round(image), 0, 255).astype(dtype)


WIRE_CODECS = {
    "mulaw": (_mulaw_encode, _mulaw_decode),
    "i8": (_i8_encode, _i8_decode),
    "i8mel": (_i8mel_encode, _i8mel_decode),
    "dct8": (_dct8_encode, _dct8_decode),
}

# The codec/dtype legality table — what each lossy codec can CARRY.
# Exported so the static checker (analysis/graph_check.py) proves remote
# hops sound before any frame moves, and enforced at encode time below
# so a wrong hint fails loudly instead of producing garbage tensors.
#   mulaw: companding of float audio in [-1, 1];
#   i8:    absmax quantization of float tensors (one f32 scale);
#   dct8:  blockwise DCT of uint8 images, shape [H, W, C].
WIRE_CODEC_DTYPES = {
    "mulaw": ("float16", "float32", "float64"),
    "i8": ("float16", "float32", "float64", "bfloat16"),
    "i8mel": ("float16", "float32", "float64", "bfloat16"),
    "dct8": ("uint8",),
}
WIRE_CODEC_RANK = {"dct8": 3, "i8mel": 2}


def codec_legal(codec: str, dtype, ndim: int | None = None) -> bool:
    """True when `codec` can legally carry an array of `dtype` (and,
    when given, rank `ndim`)."""
    allowed = WIRE_CODEC_DTYPES.get(codec)
    if allowed is None or str(dtype) not in allowed:
        return False
    rank = WIRE_CODEC_RANK.get(codec)
    return ndim is None or rank is None or ndim == rank


# -- encode ------------------------------------------------------------------

def _extract(obj, buffers, key=None, codec_hints=None):
    """Walk obj, replacing ndarray/bytes values with marker lists and
    appending their raw bytes (as memoryviews — no copy until the final
    join) to `buffers`."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        index = len(buffers)
        buffers.append(memoryview(obj).cast("B"))
        return [_MARKER, str(index), "bytes", "", [], "", []]
    if _is_arraylike(obj) and not isinstance(obj, (str, int, float, bool)):
        array = np.asarray(obj)
        codec = (codec_hints or {}).get(key, "")
        meta: list = []
        if codec:
            if codec not in WIRE_CODECS:
                raise WireError(f"unknown wire codec {codec!r}")
            if not codec_legal(codec, array.dtype, array.ndim):
                raise WireError(
                    f"wire codec {codec!r} cannot carry key {key!r} "
                    f"(dtype {array.dtype}, rank {array.ndim}; legal "
                    f"dtypes: {WIRE_CODEC_DTYPES[codec]})")
            array, meta = WIRE_CODECS[codec][0](array)
        if not array.flags.c_contiguous:
            array = np.ascontiguousarray(array)
        index = len(buffers)
        try:
            buffers.append(memoryview(array).cast("B"))
        except (ValueError, TypeError):
            # extension dtypes (bfloat16, fp8) lack the buffer
            # protocol: reinterpret the same memory as uint8
            buffers.append(memoryview(
                array.reshape(-1).view(np.uint8)).cast("B"))
        return [_MARKER, str(index), "nd", str(array.dtype),
                [str(d) for d in array.shape], codec, meta]
    if isinstance(obj, dict):
        return {k: _extract(v, buffers, key=k, codec_hints=codec_hints)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_extract(v, buffers, key=key, codec_hints=codec_hints)
                for v in obj]
    return obj


def pop_trace(parameters):
    """Strip a trailing trace-context marker from a decoded parameter
    list; returns the marker's field list or None.  Shared by the
    envelope decoder and the text-path consumers (actor layer), so both
    wire forms shed the header identically."""
    if isinstance(parameters, list) and parameters:
        last = parameters[-1]
        if isinstance(last, (list, tuple)) and last and \
                isinstance(last[0], str) and last[0] == _TRACE:
            return list(parameters.pop())
    return None


def tenant_fields(tenant, tier=1) -> list:
    """The wire form of a tenant tag: a self-tagged field list, so it
    can ride as a trailing header parameter OR as a positional hop-entry
    field without ambiguity against trace fields."""
    return [TENANT_MARKER, str(tenant), str(int(tier))]


def is_tenant_fields(value) -> bool:
    return isinstance(value, (list, tuple)) and bool(value) and \
        isinstance(value[0], str) and value[0] == TENANT_MARKER


def parse_tenant(fields, default_tier: int = 1):
    """(tenant, tier) from a tenant field list; ("", default_tier) when
    absent/malformed — the admission gate folds "" into its default
    tenant bucket."""
    if not is_tenant_fields(fields) or len(fields) < 2:
        return "", int(default_tier)
    tenant = str(fields[1])
    try:
        tier = int(fields[2]) if len(fields) > 2 else int(default_tier)
    except (TypeError, ValueError):
        tier = int(default_tier)
    return tenant, tier


def pop_tenant(parameters):
    """Strip a trailing tenant marker from a decoded parameter list;
    returns the field list or None.  Must run BEFORE pop_trace: the
    tenant marker is appended after the trace marker on encode, so it
    is the last parameter when both are present."""
    if isinstance(parameters, list) and parameters:
        if is_tenant_fields(parameters[-1]):
            return list(parameters.pop())
    return None


def encode_envelope(command: str, parameters=(), codec_hints=None,
                    trace=None, tenant=None) -> bytes:
    """RPC (command, params) -> one binary envelope payload.

    codec_hints: {dict_key: codec_name} — arrays stored under a hinted
    dict key ship through that codec (lossy, opt-in).
    trace: an optional trace-context field list (observe/tracing.py
    TraceContext.to_fields) carried in the envelope header.
    tenant: an optional tenant field list (tenant_fields) carried after
    the trace — the serving admission gate's per-tenant charge tag."""
    buffers: list[memoryview] = []
    extracted = [_extract(p, buffers, codec_hints=codec_hints)
                 for p in parameters]
    if trace:
        extracted.append([str(f) for f in trace])
    if tenant:
        extracted.append([str(f) for f in tenant])
    header = generate(command, extracted).encode("utf-8")
    parts = [MAGIC, _HEAD.pack(WIRE_VERSION, len(header)), header,
             _COUNT.pack(len(buffers))]
    for view in buffers:
        parts.append(_BUFLEN.pack(view.nbytes))
        parts.append(view)
    return b"".join(parts)


# -- decode ------------------------------------------------------------------

def _restore(obj, buffers, payload_nbytes=0):
    if isinstance(obj, list) and len(obj) == BUFFER_MARKER_ARITY \
            and obj[0] == _MARKER:
        _, index, kind, dtype, dims, codec, meta = obj
        try:
            view = buffers[int(index)]
        except (IndexError, ValueError) as exc:
            raise WireError(f"envelope buffer {index!r} missing") from exc
        if kind == "bytes":
            return bytes(view)
        if isinstance(meta, dict):            # sexpr read 2-item meta back
            meta = [k2 for pair in meta.items() for k2 in pair]
        shape = tuple(int(d) for d in dims)
        try:
            try:
                np_dtype = np.dtype(dtype)
            except TypeError:
                import ml_dtypes  # noqa: F401 — registers bfloat16/fp8
                np_dtype = np.dtype(dtype)
            array = np.frombuffer(view, dtype=np_dtype).reshape(shape)
        except WireError:
            raise
        except Exception as exc:
            raise WireError(
                f"envelope buffer {index} does not match its "
                f"dtype/shape tag ({dtype}, {shape}): {exc}") from exc
        if codec:
            if codec not in WIRE_CODECS:
                raise WireError(f"unknown wire codec {codec!r}")
            return WIRE_CODECS[codec][1](array, list(meta))
        if array.nbytes * 8 < payload_nbytes:
            # a view pins the WHOLE envelope payload alive: for a small
            # array in a large coalesced envelope (e.g. one stream's
            # tokens among many streams' replies), copying out is far
            # cheaper than retaining megabytes per retained result
            array = array.copy()
            array.flags.writeable = False     # same contract as views
        return array                          # read-only zero-copy view
    if isinstance(obj, dict):
        return {k: _restore(v, buffers, payload_nbytes)
                for k, v in obj.items()}
    if isinstance(obj, list):
        return [_restore(v, buffers, payload_nbytes) for v in obj]
    return obj


def decode_envelope(payload, with_trace: bool = False,
                    with_tenant: bool = False):
    """One binary envelope payload -> (command, params), or
    (command, params, trace_fields|None) when with_trace=True, or
    (command, params, trace, tenant_fields|None) when with_tenant=True.

    ndarrays come back as read-only views over `payload` (zero-copy);
    everything else keeps S-expression semantics (strings).  Trace and
    tenant headers (see encode_envelope) are always stripped from the
    params, whether or not the caller asks for them back."""
    view = memoryview(payload).cast("B")
    if view.nbytes < 4 + _HEAD.size or bytes(view[:4]) != MAGIC:
        raise WireError("not a binary envelope (bad magic / truncated)")
    version, header_len = _HEAD.unpack_from(view, 4)
    if version != WIRE_VERSION:
        raise WireError(f"unsupported envelope version {version}")
    offset = 4 + _HEAD.size
    if offset + header_len + _COUNT.size > view.nbytes:
        raise WireError("envelope header overruns payload")
    try:
        header = bytes(view[offset:offset + header_len]).decode("utf-8")
    except UnicodeDecodeError as exc:
        raise WireError(f"envelope header is not utf-8: {exc}") from exc
    offset += header_len
    (count,) = _COUNT.unpack_from(view, offset)
    offset += _COUNT.size
    buffers = []
    for _ in range(count):
        if offset + _BUFLEN.size > view.nbytes:
            raise WireError("envelope buffer table overruns payload")
        (length,) = _BUFLEN.unpack_from(view, offset)
        offset += _BUFLEN.size
        if offset + length > view.nbytes:
            raise WireError("envelope buffer overruns payload")
        buffers.append(view[offset:offset + length])
        offset += length
    try:
        expr = parse_sexpr(header)
    except Exception as exc:
        raise WireError(f"envelope header parse failed: {exc}") from exc
    if isinstance(expr, str):
        if with_tenant:
            return expr, [], None, None
        return (expr, [], None) if with_trace else (expr, [])
    if not isinstance(expr, list) or not expr or \
            not isinstance(expr[0], str):
        raise WireError(f"envelope header is not an RPC: {header!r}")
    params = [_restore(p, buffers, view.nbytes) for p in expr[1:]]
    tenant = pop_tenant(params)         # appended last; strip first
    trace = pop_trace(params)
    if with_tenant:
        return expr[0], params, trace, tenant
    if with_trace:
        return expr[0], params, trace
    return expr[0], params


# -- KV-transfer envelope kind (ISSUE 14) ------------------------------------
# The disaggregated prefill/decode split ships computed prompt KV from a
# prefill runtime to a decode runtime over the peer data plane.  The
# payload is an ordinary binary envelope whose command is
# KV_TRANSFER_COMMAND — it rides peer channels, the broker fallback,
# chaos seams, and tracing exactly like every other data-plane envelope
# — but its tensor fields are DECLARED here, like the codec legality
# tables above, so graft-check proves the transfer schema sound without
# importing serving and the decoder rejects a malformed transfer loudly
# instead of scattering garbage rows into a live KV cache.
#
# Wire layout (envelope params):
#   [transfer_id, tenant, start_block, block_tokens, first_token,
#    [layout fields...], {"tokens": i32[*]},
#    [ per-block [ per-layer {"k": leaf, "v": leaf} ] ]]
# where a leaf is either a native rows array ([H, B, D], the decoder's
# compute dtype) or the int8 serving form
# {"q": i8[H, B, D], "s": f32[H, B]} (layers.quantize_kv_cache) —
# carried bit-exact: the decode side installs the very bytes the donor
# decoder would have read, so greedy parity is preserved by
# construction and an int8 chain never double-rounds.

KV_TRANSFER_COMMAND = "kv_transfer"
# contract-grammar declaration (analysis/contracts.py syntax) — the
# graft-check wire-schema check parses these and verifies they agree
# with the runtime tables below, so the declaration cannot drift from
# what encode/decode actually enforce
KV_TRANSFER_SCHEMA = {
    "tokens": "i32[*]",
    "kv": "bf16[*,*,*] | f32[*,*,*] | f16[*,*,*]",
    "kv_q": "i8[*,*,*]",
    "kv_s": "f32[*,*]",
}
# runtime legality tables (the enforcement twin of the schema above)
KV_TRANSFER_DTYPES = {
    "tokens": ("int32",),
    "kv": ("bfloat16", "float32", "float16"),
    "kv_q": ("int8",),
    "kv_s": ("float32",),
}
KV_TRANSFER_RANK = {"tokens": 1, "kv": 3, "kv_q": 3, "kv_s": 2}


def kv_leaf_legal(field: str, dtype, ndim: int) -> bool:
    """True when `field` may legally carry an array of `dtype`/rank
    (the KV-transfer analogue of codec_legal)."""
    allowed = KV_TRANSFER_DTYPES.get(field)
    return allowed is not None and str(dtype) in allowed and \
        ndim == KV_TRANSFER_RANK[field]


def _check_kv_leaf(leaf, what: str):
    """Validate one K or V rows leaf against the declared schema;
    returns it unchanged.  Shared by encode (fail before bytes move)
    and decode (fail before rows could reach a cache).  Non-array
    values (a corrupt or version-drifted payload whose leaf decoded
    as a string) are a WireError too — the caller's recovery ladder
    catches WireError, not AttributeError."""
    if isinstance(leaf, dict):
        if set(leaf) != {"q", "s"}:
            raise WireError(
                f"kv_transfer {what}: int8 leaf must be {{'q','s'}}, "
                f"got keys {sorted(leaf)}")
        q, s = leaf["q"], leaf["s"]
        if not _is_nd_value(q) or not _is_nd_value(s):
            raise WireError(
                f"kv_transfer {what}: q/s must be arrays, got "
                f"{type(q).__name__}/{type(s).__name__}")
        if not kv_leaf_legal("kv_q", q.dtype, q.ndim):
            raise WireError(
                f"kv_transfer {what}: q must be "
                f"{KV_TRANSFER_SCHEMA['kv_q']}, got {q.dtype} "
                f"rank {q.ndim}")
        if not kv_leaf_legal("kv_s", s.dtype, s.ndim):
            raise WireError(
                f"kv_transfer {what}: s must be "
                f"{KV_TRANSFER_SCHEMA['kv_s']}, got {s.dtype} "
                f"rank {s.ndim}")
        if q.shape[:2] != s.shape:
            raise WireError(
                f"kv_transfer {what}: scale shape {s.shape} does not "
                f"match values {q.shape}")
        return leaf
    if not _is_nd_value(leaf) or \
            not kv_leaf_legal("kv", leaf.dtype, leaf.ndim):
        raise WireError(
            f"kv_transfer {what}: rows must be "
            f"{KV_TRANSFER_SCHEMA['kv']}, got "
            f"{getattr(leaf, 'dtype', type(leaf).__name__)} "
            f"rank {getattr(leaf, 'ndim', '?')}")
    return leaf


def _is_nd_value(value) -> bool:
    return hasattr(value, "dtype") and hasattr(value, "ndim") and \
        hasattr(value, "shape")


def encode_kv_transfer(transfer_id: str, tenant: str, tokens,
                       start_block: int, block_tokens: int,
                       layout, blocks, first_token: int | None = None,
                       trace=None, final: bool = True) -> bytes:
    """One KV-transfer envelope: `blocks` is [per block [per layer
    {"k": leaf, "v": leaf}]] covering chain blocks
    [start_block, start_block + len(blocks)) of `tokens`; blocks below
    start_block are HANDLES — the decode side already holds them (its
    chain keys are content-addressed from the tokens), so only their
    indices cross, never their bytes (ROADMAP item 3 residue b).
    `layout` is the donor decoder's storage-layout tuple
    (PrefixKVCache.layout) — the receiver refuses a geometry mismatch
    before any row lands.

    `final=False` marks a pipelined chunk-stream member (ISSUE 17):
    an optional ninth "chunk" param rides the envelope, so a pre-17
    receiver — which reads params[:8] — treats every chunk as a
    complete transfer and settles on the first one: degraded (it
    loses the stream's tail, re-prefilling it) but never wrong,
    which is what a backward-compatible wire change must be."""
    block_tokens = int(block_tokens)
    payload_blocks = []
    for b, per_layer in enumerate(blocks):
        layers = []
        for i, entry in enumerate(per_layer):
            what = f"block {b} layer {i}"
            layers.append({
                "k": _check_kv_leaf(entry["k"], what + " k"),
                "v": _check_kv_leaf(entry["v"], what + " v")})
        payload_blocks.append(layers)
    tokens = np.asarray(tokens, np.int32)
    if tokens.ndim != 1:
        raise WireError(
            f"kv_transfer tokens must be rank 1, got {tokens.ndim}")
    params = [str(transfer_id), str(tenant), str(int(start_block)),
              str(block_tokens),
              "" if first_token is None else str(int(first_token)),
              [str(f) for f in layout], {"tokens": tokens},
              payload_blocks]
    if not final:
        params.append("chunk")
    return encode_envelope(KV_TRANSFER_COMMAND, params, trace=trace)


# same-destination KV transfers coalesced into one envelope (ISSUE 15
# satellite, PR 14 residue b): the batch is a plain envelope whose
# params are the member transfers' COMPLETE encoded payloads as bytes
# fields — each member stays independently schema-checked by
# decode_kv_transfer, so a truncated member fails alone and the batch
# wrapper adds no second validation surface to keep sound
KV_BATCH_COMMAND = "kv_transfer_batch"


def encode_kv_batch(payloads, trace=None) -> bytes:
    """Coalesce encoded KV-transfer payloads into one batch envelope.
    Callers batch same-destination transfers within a short window so
    a prompt burst amortizes the per-envelope wire cost."""
    members = [bytes(p) for p in payloads]
    if not members:
        raise WireError("kv_transfer_batch with no members")
    return encode_envelope(KV_BATCH_COMMAND, [members], trace=trace)


def decode_kv_batch(payload) -> list:
    """The member payloads (bytes) of a batch envelope — decode each
    with decode_kv_transfer.  Raises WireError on a foreign command or
    non-bytes members."""
    command, params = decode_envelope(payload)
    return kv_batch_members(command, params)


def kv_batch_members(command, params) -> list:
    """Validate an already-decoded batch envelope's (command, params)
    — the shared seam for callers that decode_envelope'd once to
    dispatch on the command."""
    if command != KV_BATCH_COMMAND:
        raise WireError(f"not a kv_transfer_batch envelope: "
                        f"{command!r}")
    if not params or not isinstance(params[0], list) or not params[0]:
        raise WireError("kv_transfer_batch carries no members")
    members = params[0]
    for i, member in enumerate(members):
        if not isinstance(member, (bytes, bytearray)):
            raise WireError(
                f"kv_transfer_batch member {i} is "
                f"{type(member).__name__}, want bytes")
    return [bytes(m) for m in members]


def decode_kv_transfer(payload):
    """Decode + validate one KV-transfer envelope.  Returns a dict
    {transfer_id, tenant, start_block, block_tokens, first_token,
    layout, tokens, blocks} with every leaf schema-checked (dtype,
    rank, scale/value agreement, uniform block length) — a truncated or
    foreign payload raises WireError instead of reaching a cache."""
    command, params = decode_envelope(payload)
    return validate_kv_transfer_params(command, params)


def validate_kv_transfer_params(command, params):
    """The validation body of decode_kv_transfer over an
    already-decoded (command, params) — shared with the batch path so
    members are checked by exactly the same code."""
    if command != KV_TRANSFER_COMMAND:
        raise WireError(f"not a kv_transfer envelope: {command!r}")
    if len(params) < KV_TRANSFER_PARAMS:
        raise WireError(f"kv_transfer envelope short: {len(params)} "
                        f"params")
    (transfer_id, tenant, start_block, block_tokens, first_token,
     layout, token_box, blocks) = params[:KV_TRANSFER_PARAMS]
    try:
        start_block = int(str(start_block))
        block_tokens = int(str(block_tokens))
        first_token = None if str(first_token) == "" \
            else int(str(first_token))
    except (TypeError, ValueError) as exc:
        raise WireError(f"kv_transfer header fields malformed: "
                        f"{exc}") from exc
    if block_tokens < 1 or start_block < 0:
        raise WireError(
            f"kv_transfer header out of range: start_block "
            f"{start_block}, block_tokens {block_tokens}")
    tokens = (token_box or {}).get("tokens") \
        if isinstance(token_box, dict) else None
    if tokens is None or not _is_nd_value(tokens) or \
            not kv_leaf_legal("tokens", tokens.dtype, tokens.ndim):
        raise WireError("kv_transfer tokens missing or not i32[*]")
    if not isinstance(blocks, list):
        raise WireError("kv_transfer blocks must be a list")
    checked = []
    for b, per_layer in enumerate(blocks):
        if not isinstance(per_layer, list) or not per_layer:
            raise WireError(f"kv_transfer block {b} empty")
        layers = []
        for i, entry in enumerate(per_layer):
            if not isinstance(entry, dict) or \
                    set(entry) != {"k", "v"}:
                raise WireError(
                    f"kv_transfer block {b} layer {i}: want "
                    f"{{'k','v'}}")
            what = f"block {b} layer {i}"
            k = _check_kv_leaf(entry["k"], what + " k")
            v = _check_kv_leaf(entry["v"], what + " v")
            for name, leaf in (("k", k), ("v", v)):
                rows = leaf["q"] if isinstance(leaf, dict) else leaf
                if rows.shape[1] != block_tokens:
                    raise WireError(
                        f"kv_transfer {what} {name}: {rows.shape[1]} "
                        f"rows, want block_tokens={block_tokens}")
            layers.append({"k": k, "v": v})
        checked.append(layers)
    return {
        "transfer_id": str(transfer_id), "tenant": str(tenant),
        "start_block": start_block, "block_tokens": block_tokens,
        "first_token": first_token,
        "layout": tuple(str(f) for f in (layout or [])),
        "tokens": tokens, "blocks": checked,
        # chunk streaming (ISSUE 17): a ninth "chunk" param marks a
        # non-final stream member; anything else (including absence —
        # every pre-17 sender) is a complete transfer
        "final": not (len(params) > KV_TRANSFER_PARAMS
                      and str(params[KV_TRANSFER_PARAMS]) == "chunk"),
    }


# -- session KV migration (ISSUE 19) -----------------------------------------
# Graceful drain ships a session's pinned prefix chain to a drain
# destination as ordinary chunk-streamed KV_TRANSFER envelopes; the
# control legs around those transfers are three tiny envelopes of their
# own.  The offer carries BOTH token lists a session owns — the pinned
# chain's tokens (what the KV blocks cover) and the conversation
# history (what the SessionTable payload holds) — so the destination
# can re-pin AND re-create the session record in one landing.
#
#   offer (source -> destination /migrate):
#     [transfer_id, tenant, sid, reply_topic,
#      {"tokens": i32[*]}, {"history": i32[*]}]
#   ack (destination -> reply_topic): [transfer_id, have_blocks]
#     — have_blocks leading chain blocks are already resident at the
#     destination (content-addressed), so the source ships handles
#     below that mark, bytes above it
#   done (destination -> reply_topic): [transfer_id, installed_blocks]

KV_MIGRATE_COMMAND = "kv_migrate"
KV_MIGRATE_ACK_COMMAND = "kv_migrate_ack"
KV_MIGRATE_DONE_COMMAND = "kv_migrate_done"
KV_MIGRATE_PARAMS = 6       # offer's required param count


def encode_kv_migrate(transfer_id: str, tenant: str, sid: str,
                      reply_topic: str, tokens, history,
                      trace=None) -> bytes:
    """One session-migration offer envelope (see layout above)."""
    tokens = np.asarray(tokens, np.int32)
    history = np.asarray(history, np.int32)
    if tokens.ndim != 1 or history.ndim != 1:
        raise WireError(
            f"kv_migrate tokens/history must be rank 1, got "
            f"{tokens.ndim}/{history.ndim}")
    return encode_envelope(
        KV_MIGRATE_COMMAND,
        [str(transfer_id), str(tenant), str(sid), str(reply_topic),
         {"tokens": tokens}, {"history": history}], trace=trace)


def validate_kv_migrate_params(command, params):
    """Decode-side twin of encode_kv_migrate: returns {transfer_id,
    tenant, sid, reply_topic, tokens, history} with both arrays
    schema-checked, or raises WireError."""
    if command != KV_MIGRATE_COMMAND:
        raise WireError(f"not a kv_migrate envelope: {command!r}")
    if len(params) < KV_MIGRATE_PARAMS:
        raise WireError(
            f"kv_migrate envelope short: {len(params)} params")
    (transfer_id, tenant, sid, reply_topic,
     token_box, history_box) = params[:KV_MIGRATE_PARAMS]
    arrays = {}
    for name, box in (("tokens", token_box), ("history", history_box)):
        value = (box or {}).get(name) if isinstance(box, dict) else None
        if value is None or not _is_nd_value(value) or \
                not kv_leaf_legal("tokens", value.dtype, value.ndim):
            raise WireError(f"kv_migrate {name} missing or not i32[*]")
        arrays[name] = value
    return {"transfer_id": str(transfer_id), "tenant": str(tenant),
            "sid": str(sid), "reply_topic": str(reply_topic),
            "tokens": arrays["tokens"], "history": arrays["history"]}


def encode_kv_migrate_reply(command: str, transfer_id: str,
                            blocks: int, trace=None) -> bytes:
    """Ack/done control leg: [transfer_id, blocks] under `command`
    (KV_MIGRATE_ACK_COMMAND or KV_MIGRATE_DONE_COMMAND)."""
    if command not in (KV_MIGRATE_ACK_COMMAND, KV_MIGRATE_DONE_COMMAND):
        raise WireError(f"not a kv_migrate reply command: {command!r}")
    return encode_envelope(command,
                           [str(transfer_id), str(int(blocks))],
                           trace=trace)


def validate_kv_migrate_reply(command, params) -> tuple:
    """(transfer_id, blocks) of an ack/done leg, or WireError."""
    if command not in (KV_MIGRATE_ACK_COMMAND, KV_MIGRATE_DONE_COMMAND):
        raise WireError(f"not a kv_migrate reply envelope: {command!r}")
    if len(params) < 2:
        raise WireError(
            f"kv_migrate reply short: {len(params)} params")
    try:
        blocks = int(str(params[1]))
    except (TypeError, ValueError) as exc:
        raise WireError(
            f"kv_migrate reply blocks malformed: {exc}") from exc
    if blocks < 0:
        raise WireError(f"kv_migrate reply blocks negative: {blocks}")
    return str(params[0]), blocks


def encode_rpc(command: str, parameters=(), transport=None,
               codec_hints=None, trace=None, tenant=None):
    """Pick the wire representation for an outbound RPC: the binary
    envelope when the transport can carry bytes AND the params hold
    binary values; S-expression text otherwise (control-plane messages
    stay human-readable, non-binary transports keep working).  Trace
    and tenant field lists ride the envelope header on the binary path
    and as trailing marker parameters on the text path — decoders strip
    them either way (pop_trace / pop_tenant)."""
    if supports_binary(transport) and contains_binary(parameters):
        return encode_envelope(command, parameters,
                               codec_hints=codec_hints, trace=trace,
                               tenant=tenant)
    text_params = [
        p if not _is_arraylike(p) or isinstance(p, (str, int, float, bool))
        else generate_sexpr(np.asarray(p).tolist()) for p in parameters]
    if trace:
        text_params.append([str(f) for f in trace])
    if tenant:
        text_params.append([str(f) for f in tenant])
    return generate(command, text_params)
