# Loopback paho surface: an in-process "MQTT broker" and the paho-v2
# client face MQTTMessage drives, with no network and no daemon.
#
# This is the harness behind the MQTT transport tests (tests/
# test_mqtt.py) and the chaos soak's --mqtt variant (scripts/
# chaos_soak.py): the full MQTTMessage code path — connect callbacks,
# subscriptions, retained state, LWT on ungraceful drops, reconnect
# backoff — runs against a deterministic broker this process owns.  It
# lives in the package (not tests/) because the soak script is a
# first-class consumer; production code never imports it.

from __future__ import annotations

from .message import topic_matches

__all__ = ["LoopbackBroker", "LoopbackPaho"]


class LoopbackBroker:
    """Minimal broker shared by LoopbackPaho clients: routes published
    messages to subscribed clients, fires LWT on ungraceful drops."""

    def __init__(self):
        self.clients = []
        self.down = False          # simulates broker/network outage
        self.retained = {}

    def route(self, topic, payload, retain=False):
        if retain:                 # MQTT: empty retained payload clears
            if payload in ("", b""):
                self.retained.pop(topic, None)
            else:
                self.retained[topic] = payload
        for client in list(self.clients):
            if not client.connected_to_broker:
                continue
            for pattern in list(client.subscriptions):
                if topic_matches(pattern, topic):
                    client.deliver(topic, payload)
                    break

    def send_retained(self, client, pattern):
        for topic, payload in list(self.retained.items()):
            if topic_matches(pattern, topic):
                client.deliver(topic, payload)


class _PublishInfo:
    def wait_for_publish(self, timeout=None):
        return True


class LoopbackPaho:
    """The paho v2 client surface MQTTMessage uses."""

    def __init__(self, broker: LoopbackBroker):
        self.broker = broker
        self.subscriptions = set()
        self.connected_to_broker = False
        self.will = None
        self.on_connect = None
        self.on_disconnect = None
        self.on_message = None
        self.connect_attempts = 0
        broker.clients.append(self)

    # -- connection --------------------------------------------------------
    def connect(self, host, port):
        self.connect_attempts += 1
        if self.broker.down:
            raise ConnectionRefusedError("broker down")
        self.connected_to_broker = True
        # paho fires on_connect from its network thread post-connect
        if self.on_connect:
            self.on_connect(self, None, None, 0)

    def reconnect(self):
        self.subscriptions.clear()     # clean session: broker state gone
        self.connect(None, None)

    def disconnect(self):
        # graceful: no LWT
        was = self.connected_to_broker
        self.connected_to_broker = False
        if was and self.on_disconnect:
            self.on_disconnect(self, None, None, 0)

    def drop(self):
        """Ungraceful loss (network cut): broker publishes the LWT."""
        self.connected_to_broker = False
        if self.will:
            self.broker.route(*self.will)
        if self.on_disconnect:
            self.on_disconnect(self, None, None, 7)

    def loop_start(self):
        pass

    def loop_stop(self):
        pass

    # -- messaging ----------------------------------------------------------
    def subscribe(self, topic):
        # real brokers resend retained state on EVERY SUBSCRIBE packet
        # (not just the first): a late-joining host must receive the
        # retained registrar boot record on its connect resubscribe
        self.subscriptions.add(topic)
        if self.connected_to_broker:
            self.broker.send_retained(self, topic)

    def unsubscribe(self, topic):
        self.subscriptions.discard(topic)

    def publish(self, topic, payload, retain=False):
        self.broker.route(topic, payload, retain)
        return _PublishInfo()

    def deliver(self, topic, payload):
        if self.on_message:
            message = type("M", (), {"topic": topic,
                                     "payload": payload.encode()
                                     if isinstance(payload, str)
                                     else payload})
            self.on_message(self, None, message)

    def will_set(self, topic, payload, retain=False):
        self.will = (topic, payload, retain)

    def username_pw_set(self, username, password):
        pass
