# Paged KV block pool for continuous-batching serving (ISSUE 15,
# ROADMAP item 3 residue c).
#
# The dense slot cache ([S, H, T, D] per layer) made three subsystems
# move KV by COPY: a prefix-cache hit copied the cached chain's rows
# into the slot, harvest copied them back out at retire, and the
# disaggregated install paid the same copy on top of the wire transfer.
# vLLM's PagedAttention (Kwon et al., SOSP 2023) is the fix: ONE pool
# of fixed-size token blocks per layer plus per-slot int32 block
# tables, so "this slot holds that prefix" is a table edit over
# refcounted blocks, not a row movement —
#
#   * a prefix hit ALIASES the cached chain's pool blocks into the
#     slot's table (retain refs; zero bytes move);
#   * harvest is "retain + record key" — the slot's own blocks BECOME
#     the cache entries (the double write is gone);
#   * the disaggregated install (DistServe, OSDI 2024) writes shipped
#     blocks straight into pool blocks once — later admits are table
#     edits;
#   * copy-on-extend: writing into a SHARED block (refs > 1 — e.g. the
#     near-seq-cap final-chunk slide-back into a cached region) first
#     copies it to a fresh block, so aliased readers never see a
#     mutation.  At most one partial block copies per such write; the
#     common hit path copies nothing.
#
# Device-side discipline: the compiled step GATHERS a slot-major
# [S, H, T, D] view from the pool once per round (the main cache is
# read-only through the scan, so the gather hoists out of it), slices
# it to the dense path's exact time extent, and runs the SAME attention
# bodies (_slot_attention_block / _slot_attention_spec) — the gathered
# view is value- and shape-identical to the dense slot cache, so paged
# greedy output is BIT-IDENTICAL to dense by construction.  Round-end
# side-buffer merges scatter to (block, offset) pairs computed from the
# tables, with out-of-range ids dropping exactly like the dense path's
# _POS_INVALID entries.  This module owns the pool allocator and the
# paged compiled-program builders; serving.ContinuousDecoder(
# paged_kv=True) is the integration point and keeps the dense path as
# the A/B (AIKO_BENCH_LLAMA_PAGED=off).

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .models import layers as L
from .models.llama import LlamaConfig, llama_ffn
from .utils import get_logger

__all__ = ["BlockPool"]


class BlockPool:
    """Device-resident paged KV block pool + host-side refcounting
    allocator.

    One pool id addresses one `block_tokens`-token block ACROSS the
    whole model: k_pools[i][id] / v_pools[i][id] are layer i's K/V rows
    for that block ([H, B, D] native, or the int8 serving form
    {"q" i8 [H, B, D], "s" f32 [H, B]}).  Block 0 is the reserved NULL
    block (all zeros, never allocated): unfilled table entries point at
    it, so gathers stay in bounds and read only masked positions.

    Refcounts count LOGICAL OWNERS — slot tables, prefix-cache nodes,
    in-flight installs.  alloc_blocks() hands out refs=1 ids (growing
    the device arrays in `grow_blocks` steps when the free list runs
    dry — the paged sibling of _fit_caches' grow); retain()/
    release_blocks() move ownership; refs hitting zero returns the id
    to the free list with its contents left in place (stale rows are
    only ever gathered at masked positions until the next owner
    overwrites them, the same dead-cell invariant as the dense cache).

    Single-threaded like the decoder that owns it (pump runs on the
    event engine)."""

    def __init__(self, config: LlamaConfig, block_tokens: int,
                 kv_int8: bool, initial_blocks: int = 64,
                 grow_blocks: int = 64, name: str = "pool",
                 registry=None):
        self.config = config
        self.block_tokens = int(block_tokens)
        if self.block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        self.kv_int8 = bool(kv_int8)
        self.name = str(name)
        self.grow_blocks = max(1, int(grow_blocks))
        self.logger = get_logger(f"serving.pool.{name}")
        n = max(2, int(initial_blocks) + 1)          # +1: null block
        self.num_blocks = n
        self.k_pools = self._zero_pools(n)
        self.v_pools = self._zero_pools(n)
        self._refs = np.zeros((n,), np.int32)
        self._free = list(range(n - 1, 0, -1))       # 0 reserved
        itemsize = jnp.dtype(config.dtype).itemsize
        per_position = (config.head_dim + 4) if self.kv_int8 \
            else config.head_dim * itemsize
        # K + V, all layers, one block's tokens — the budget currency
        self.block_nbytes = (2 * config.num_layers *
                             config.num_kv_heads * per_position *
                             self.block_tokens)
        from .observe.metrics import MirroredStats, default_registry
        self._registry = registry or default_registry()
        self.stats = MirroredStats(
            {"allocs": 0, "frees": 0, "grows": 0, "shrinks": 0,
             "cow_copies": 0, "cow_copy_bytes": 0,
             "install_blocks": 0, "install_bytes": 0},
            metric="kv_pool_events_total",
            help="paged KV block-pool events by kind",
            registry=self._registry,
            skip=("cow_copy_bytes", "install_bytes"),
            labels={"pool": self.name})
        self._gauge_total = self._registry.gauge(
            "kv_pool_blocks", "paged KV pool capacity in blocks",
            labels={"pool": self.name})
        self._gauge_used = self._registry.gauge(
            "kv_pool_blocks_used",
            "paged KV pool blocks with at least one owner",
            labels={"pool": self.name})
        self._gauge_occupancy = self._registry.gauge(
            "kv_pool_occupancy",
            "used / capacity fraction of the paged KV pool",
            labels={"pool": self.name})
        self._used = 0
        # KV memory ledger (ISSUE 20): when attached, every PHYSICAL
        # transition (alloc, refs 1->0 release) reports tenant-
        # attributed byte deltas — retains are ownership moves and
        # stay invisible, so ledger totals conserve against
        # used_blocks() * block_nbytes by construction
        self._ledger = None
        # shrink floor: construction capacity, raised by reserve() —
        # maybe_shrink never retraces below what a caller declared as
        # steady state, so drain/refill cycles don't thrash shapes
        self._floor_blocks = n
        self._publish_gauges()

    def attach_ledger(self, ledger) -> None:
        self._ledger = ledger
        if ledger is not None:
            ledger.attach_pool(self)

    # -- device arrays -----------------------------------------------------
    def _zero_pools(self, n: int) -> list:
        config = self.config
        shape = (n, config.num_kv_heads, self.block_tokens,
                 config.head_dim)
        if self.kv_int8:
            return [{"q": jnp.zeros(shape, jnp.int8),
                     "s": jnp.zeros(shape[:3], jnp.float32)}
                    for _ in range(config.num_layers)]
        return [jnp.zeros(shape, config.dtype)
                for _ in range(config.num_layers)]

    def nbytes(self) -> int:
        """Bytes currently allocated to the pool device arrays — what
        ContinuousDecoder.kv_cache_bytes() reports in paged mode."""
        return int(sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for pools in (self.k_pools, self.v_pools)
            for pool in pools
            for leaf in jax.tree_util.tree_leaves(pool)))

    def _grow(self, need: int) -> None:
        # GEOMETRIC growth (at least doubling): every distinct pool
        # capacity is a fresh shape for every compiled program that
        # touches it, so linear growth would retrace the whole
        # step/admit/extend family once per increment — measured as a
        # 10x cold-TTFT inflation on the conversation rung.  Doubling
        # bounds the retrace count to O(log blocks), the same
        # discipline as _fit_caches' t_block quantization.
        extra = -(-max(need, 1) // self.grow_blocks) * self.grow_blocks
        extra = max(extra, self.num_blocks - 1)
        old_n, new_n = self.num_blocks, self.num_blocks + extra
        grow = _pool_grow_fn(old_n, new_n)
        self.k_pools = grow(self.k_pools)
        self.v_pools = grow(self.v_pools)
        self._free.extend(range(new_n - 1, old_n - 1, -1))
        # geometric growth is amortized O(log blocks) and reserve()
        # pre-warms steady state out of the serving window entirely
        self._refs = np.concatenate(  # graft: disable=lint-hot-alloc
            [self._refs, np.zeros((extra,), np.int32)])
        self.num_blocks = new_n
        self.stats["grows"] += 1
        self._publish_gauges()

    def reserve(self, capacity: int) -> None:
        """Grow the pool to at least `capacity` allocatable blocks NOW
        (no allocation).  Every distinct pool capacity is a fresh
        shape for the compiled programs, so callers that can predict
        steady-state residency (slot coverage + prefix-cache budget)
        reserve it up front and keep growth retraces out of the
        serving window."""
        self._floor_blocks = max(self._floor_blocks,
                                 int(capacity) + 1)
        short = int(capacity) - (self.num_blocks - 1)
        if short > 0:
            self._grow(short)

    def maybe_shrink(self, watermark: float = 0.25) -> int:
        """Idle-watermark release (ISSUE 16 satellite): when occupancy
        has fallen to `watermark` or below — a tenant drain — release
        the pool's FREE TAIL back to the allocator so steady-state HBM
        stays honest after a burst.  Returns blocks released (0 when
        the watermark, floor, or geometric hysteresis says no).

        Only the tail [keep, num_blocks) can go: block ids are array
        positions, so reclaiming interior free blocks would mean
        compacting live contents and rewriting every owner's table.
        The release is geometric (at least halving, mirroring _grow's
        doubling) and never cuts below the reserve()/construction
        floor — a capacity change retraces every compiled program
        that touches the pool, so callers gate this on IDLE (the
        decoder's pump does) and the hysteresis keeps it rare."""
        capacity = self.num_blocks - 1
        if capacity <= 0 or self._used > watermark * capacity:
            return 0
        keep = self.num_blocks
        while keep > self._floor_blocks and self._refs[keep - 1] == 0:
            keep -= 1
        released = self.num_blocks - keep
        if released * 2 < self.num_blocks:
            return 0
        shrink = _pool_shrink_fn(keep)
        self.k_pools = shrink(self.k_pools)
        self.v_pools = shrink(self.v_pools)
        self._free = [i for i in self._free if i < keep]
        self._refs = self._refs[:keep]
        self.num_blocks = keep
        self.stats["shrinks"] += 1
        self._publish_gauges()
        self.logger.info("pool %s shrank by %d blocks to %d",
                         self.name, released, keep - 1)
        return released

    # -- allocator ---------------------------------------------------------
    def alloc_blocks(self, count: int, tenant: str = "") -> list:
        """`count` fresh block ids, each with refs=1 owned by the
        caller.  Grows the device pools when the free list runs dry.
        `tenant` attributes the bytes in the KV ledger (ISSUE 20) —
        accounting only, allocation behavior is tenant-blind."""
        count = int(count)
        if count <= 0:
            return []
        if len(self._free) < count:
            self._grow(count - len(self._free))
        ids = [self._free.pop() for _ in range(count)]
        for block_id in ids:
            self._refs[block_id] = 1
        self._used += count
        self.stats["allocs"] += count
        if self._ledger is not None:
            self._ledger.device_delta(
                tenant, count * self.block_nbytes, "alloc")
        self._publish_gauges()
        return ids

    def retain(self, ids) -> None:
        for block_id in ids:
            if not 0 < block_id < self.num_blocks or \
                    self._refs[block_id] <= 0:
                raise ValueError(
                    f"pool {self.name!r}: retain of dead block "
                    f"{block_id}")
            self._refs[block_id] += 1

    def release_blocks(self, ids, tenant: str = "") -> None:
        """Drop one ref per id; refs hitting zero return the id to the
        free list (contents stay — dead cells until reallocated).
        `tenant` attributes the freed bytes in the KV ledger — only
        the refs 1->0 transitions are physical."""
        freed = 0
        for block_id in ids:
            if not 0 < block_id < self.num_blocks:
                continue
            refs = self._refs[block_id]
            if refs <= 0:
                raise ValueError(
                    f"pool {self.name!r}: release of free block "
                    f"{block_id}")
            self._refs[block_id] = refs - 1
            if refs == 1:
                self._free.append(block_id)
                freed += 1
        if freed:
            self._used -= freed
            self.stats["frees"] += freed
            if self._ledger is not None:
                self._ledger.device_delta(
                    tenant, -freed * self.block_nbytes, "release")
            self._publish_gauges()

    def refs(self, block_id: int) -> int:
        return int(self._refs[block_id])

    def used_blocks(self) -> int:
        """Blocks with at least one live owner (null block excluded).
        The refs scan stays the AUDIT surface (drain/leak tests);
        the hot path publishes the incremental `_used` twin, which
        alloc (every 0->1) and release (every 1->0) keep exact."""
        return int((self._refs[1:] > 0).sum())

    def occupancy(self) -> float:
        capacity = self.num_blocks - 1
        return self.used_blocks() / capacity if capacity else 0.0

    def tail_free_blocks(self) -> int:
        """Length of the pool's free TAIL — the only span
        maybe_shrink can release (ids are array positions).  The
        tiered-KV interplay surface (ISSUE 17): a demotion wave frees
        device blocks via release_blocks, and this reports how much
        of that release the NEXT idle shrink can actually give back
        (interior frees fragment until their tail neighbours drain
        too)."""
        keep = self.num_blocks
        while keep > 1 and self._refs[keep - 1] == 0:
            keep -= 1
        return self.num_blocks - keep

    def _publish_gauges(self) -> None:
        # alloc/release land here once per pump-path transition: an
        # O(num_blocks) used_blocks() scan per one-block allocation
        # would grow per-round host work with pool capacity
        capacity = self.num_blocks - 1
        self._gauge_total.set(capacity)
        self._gauge_used.set(self._used)
        self._gauge_occupancy.set(
            self._used / capacity if capacity else 0.0)

    # -- block content movement --------------------------------------------
    def copy_blocks(self, src_ids, dst_ids) -> int:
        """Device-copy block contents src -> dst (copy-on-extend): one
        batched program per call.  Returns the bytes copied — the
        number the paged A/B is meant to shrink to at most one partial
        block per shared write."""
        if not src_ids:
            return 0
        src = jnp.asarray(list(src_ids), jnp.int32)
        dst = jnp.asarray(list(dst_ids), jnp.int32)
        copy = _copy_blocks_fn(self.config, self.kv_int8)
        self.k_pools = copy(self.k_pools, src, dst)
        self.v_pools = copy(self.v_pools, src, dst)
        copied = len(src_ids) * self.block_nbytes
        self.stats["cow_copies"] += len(src_ids)
        self.stats["cow_copy_bytes"] += copied
        return copied

    def write_blocks(self, ids, k_layers, v_layers) -> None:
        """Install host block rows directly into pool blocks (the
        disaggregated KV landing, ISSUE 15): `k_layers`/`v_layers` are
        per-layer stacks covering len(ids) blocks —
        [M, H, B, D] arrays or {"q" [M, H, B, D], "s" [M, H, B]} dicts
        — written as ONE scatter per layer, so a shipped chain costs
        one device transfer per layer instead of one per leaf."""
        if not ids:
            return
        dst = jnp.asarray(list(ids), jnp.int32)
        write = _write_blocks_fn(self.config, self.kv_int8)
        as_device = _as_device_rows
        self.k_pools = write(self.k_pools, dst,
                             [as_device(rows) for rows in k_layers])
        self.v_pools = write(self.v_pools, dst,
                             [as_device(rows) for rows in v_layers])
        self.stats["install_blocks"] += len(ids)
        self.stats["install_bytes"] += len(ids) * self.block_nbytes

    def block_rows(self, block_id: int) -> tuple:
        """(per-layer K leaves, per-layer V leaves) for one block —
        device-side slice views in the pool's storage layout (the read
        behind shipping a pool-resident cache block over the wire)."""
        return ([L.slice_paged_block(pool, block_id)
                 for pool in self.k_pools],
                [L.slice_paged_block(pool, block_id)
                 for pool in self.v_pools])


def _as_device_rows(rows):
    if isinstance(rows, dict):
        return {"q": jnp.asarray(rows["q"]),
                "s": jnp.asarray(rows["s"])}
    return jnp.asarray(rows)


@functools.lru_cache(maxsize=32)
def _pool_grow_fn(old_n: int, new_n: int):
    pad = new_n - old_n

    def grow_leaf(leaf):
        spec = [(0, 0)] * leaf.ndim
        spec[0] = (0, pad)
        return jnp.pad(leaf, spec)

    def grow(pools):
        return [jax.tree.map(grow_leaf, pool) for pool in pools]

    return jax.jit(grow, donate_argnums=(0,))


@functools.lru_cache(maxsize=32)
def _pool_shrink_fn(new_n: int):
    def shrink(pools):
        return [jax.tree.map(lambda leaf: leaf[:new_n], pool)
                for pool in pools]

    return jax.jit(shrink, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _copy_blocks_fn(config: LlamaConfig, kv_int8: bool):
    def copy(pools, src, dst):
        def copy_leaf(leaf):
            return leaf.at[dst].set(jnp.take(leaf, src, axis=0),
                                    mode="drop")
        return [jax.tree.map(copy_leaf, pool) for pool in pools]

    return jax.jit(copy, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _write_blocks_fn(config: LlamaConfig, kv_int8: bool):
    def write(pools, dst, rows):
        out = []
        for pool, layer_rows in zip(pools, rows):
            if isinstance(pool, dict):
                out.append({
                    "q": pool["q"].at[dst].set(layer_rows["q"],
                                               mode="drop"),
                    "s": pool["s"].at[dst].set(layer_rows["s"],
                                               mode="drop")})
            else:
                out.append(pool.at[dst].set(layer_rows, mode="drop"))
        return out

    return jax.jit(write, donate_argnums=(0,))


# -- compiled paged programs --------------------------------------------------

def _slice_time(cache, t_cap: int):
    """Slice a gathered slot-major view to the dense path's exact time
    extent — shape-identical programs are how paged stays bit-identical
    to dense (an extra masked tail could re-pair the f32 reductions)."""
    if isinstance(cache, dict):
        return {"q": cache["q"][:, :, :t_cap],
                "s": cache["s"][:, :, :t_cap]}
    return cache[:, :, :t_cap]


def _gather_views(pools, tables, t_cap: int) -> list:
    return [_slice_time(L.gather_paged_kv(pool, tables), t_cap)
            for pool in pools]


# -- pallas kernel attention (ISSUE 16) ---------------------------------------
# AIKO_DECODE_ATTENTION=paged_kernel swaps the gather+shared-body
# attention for ops.paged_attention.paged_decode_attention: the pool
# leaves and the round table go to the kernel directly, so the
# slot-major [S, H, T, D] gather never materializes.  The gather path
# stays the bit-parity ORACLE — tests prove greedy token identity per
# (int8 × chunked × spec × block size) combination, and the kernel
# builders key their lru caches on the toggle so both variants coexist
# in one process (tools/ab_decode_attention.py flips per case).

def _table_cap(tables, block_tokens: int, t_cap: int):
    """Slice a round table to the blocks covering t_cap — an int32
    table slice, not a KV gather; the kernel masks positions against
    entry_lengths natively, so this is the only t_cap handling the
    kernel path needs."""
    return tables[:, :-(-t_cap // block_tokens)]


def _kernel_grouped_attention(layer, config: LlamaConfig, x, cos, sin,
                              k_pool, v_pool, tables, k_side, v_side,
                              entry_lengths, lengths, write_index,
                              side_valid):
    """Kernel-path sibling of serving._grouped_block_attention: the
    same QKV projection / rope / side-buffer write, then the fused
    paged kernel instead of the gathered-view einsums.  `side_valid`
    is the caller's per-query mask in its compact [S, W, P] form (the
    kernel broadcasts it over heads and groups) — one kernel serves
    the plain scan (W=1) and the widened speculative verify
    (W=1+k)."""
    from .ops.paged_attention import paged_decode_attention
    from .serving import _project_qkv
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    q, k, v = _project_qkv(layer, config, x)
    q = L.apply_rope(q, cos, sin, lengths)
    k = L.apply_rope(k, cos, sin, lengths)
    k_side = jax.lax.dynamic_update_slice_in_dim(k_side, k,
                                                 write_index, axis=2)
    v_side = jax.lax.dynamic_update_slice_in_dim(v_side, v,
                                                 write_index, axis=2)
    slots_n, num_q, head_dim = q.shape[0], q.shape[2], q.shape[3]
    group = num_heads // num_kv
    q_grouped = q.reshape(slots_n, num_kv, group * num_q, head_dim)
    out = paged_decode_attention(q_grouped, k_pool, v_pool, tables,
                                 k_side, v_side, side_valid,
                                 entry_lengths, groups=group)
    out = out.reshape(slots_n, num_heads, num_q,
                      head_dim).astype(x.dtype)
    return (L.linear(layer["attn"]["o"], L._merge_heads(out)),
            k_side, v_side)


def _kernel_attention_block(tables, layer, config: LlamaConfig, x,
                            cos, sin, k_pool, v_pool, k_side, v_side,
                            entry_lengths, lengths, step_index):
    """Kernel sibling of serving._slot_attention_block — the same side
    mask, in [S, 1, P] form."""
    side_positions = jnp.arange(k_side.shape[2])
    side_valid = ((side_positions[None] <= step_index) &
                  (side_positions[None] <
                   (lengths - entry_lengths + 1)[:, None]))[:, None, :]
    return _kernel_grouped_attention(layer, config, x, cos, sin,
                                     k_pool, v_pool, tables, k_side,
                                     v_side, entry_lengths, lengths,
                                     step_index, side_valid)


def _kernel_attention_spec(tables, layer, config: LlamaConfig, x, cos,
                           sin, k_pool, v_pool, k_side, v_side,
                           pos_side, entry_lengths, lengths, base):
    """Kernel sibling of serving._slot_attention_spec: the in-kernel
    speculative verify is just the same kernel at W = 1 + k with the
    pos_side <= q_pos causal mask — no second variant.  Signature
    matches _slot_attention_spec after the leading `tables` partial,
    so serving._spec_scan_body takes it via its attention= seam."""
    width = x.shape[1]
    q_pos = lengths[:, None] + jnp.arange(width)[None]       # [S, w]
    side_valid = pos_side[:, None, :] <= q_pos[:, :, None]   # [S,w,P]
    return _kernel_grouped_attention(layer, config, x, cos, sin,
                                     k_pool, v_pool, tables, k_side,
                                     v_side, entry_lengths, lengths,
                                     base, side_valid)


def _paged_scatter(pools, tables, positions, live, sides, kv_int8,
                   block_tokens: int):
    """Scatter side-buffer rows into pool blocks at absolute
    `positions` ([S, W]; rows where `live` is False drop).  int8 pools
    quantize the side rows ONCE here, mirroring the dense merge."""
    nb = tables.shape[1]
    num_total = jax.tree_util.tree_leaves(pools[0])[0].shape[0]
    blocks = positions // block_tokens
    offsets = positions % block_tokens
    dest = jnp.take_along_axis(tables, jnp.clip(blocks, 0, nb - 1),
                               axis=1)
    dest = jnp.where(live & (blocks >= 0) & (blocks < nb), dest,
                     num_total)
    out = []
    for pool, side in zip(pools, sides):
        rows = L.quantize_kv_cache(side) if kv_int8 else side
        out.append(L.scatter_paged_rows(pool, dest, offsets, rows))
    return out


def _build_paged_step(config: LlamaConfig, kernel: bool = False):
    """Paged sibling of serving._build_step's block-KV variant: gather
    the slot-major KV views from the pool (once — the main cache is
    read-only through the scan), run the IDENTICAL scan body
    (_slot_attention_block owns the numerics), and merge the round's
    side buffers back by (block, offset) scatter.  t_cap is static and
    equals the dense path's cache time extent, so every einsum shape
    matches the dense program exactly.

    kernel=True swaps the gather + shared attention body for the
    fused pallas kernel reading pool blocks through the table
    (_kernel_attention_block); the scan structure, side buffers, and
    merge are unchanged, and the gather path remains the parity
    oracle."""
    from .serving import _slot_attention_block, _token_block_argmax
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)

    def step(params, tokens, lengths, active, budgets, k_pools,
             v_pools, tables, num_steps, eos, t_cap):
        block_tokens = \
            jax.tree_util.tree_leaves(k_pools[0])[0].shape[2]
        if kernel:
            k_caches = v_caches = None
            cap_tables = _table_cap(tables, block_tokens, t_cap)
        else:
            k_caches = _gather_views(k_pools, tables, t_cap)
            v_caches = _gather_views(v_pools, tables, t_cap)
        entry_lengths = lengths
        entry_active = active
        slots_n = tokens.shape[0]
        side_shape = (slots_n, config.num_kv_heads, num_steps,
                      config.head_dim)
        k_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        v_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]

        def body(carry, step_index):
            tokens, lengths, active, budgets, k_sides, v_sides = carry
            new_k, new_v = [], []

            def attend(i, layer, normed):
                if kernel:
                    attn_out, k_s, v_s = _kernel_attention_block(
                        cap_tables, layer, config, normed, cos, sin,
                        k_pools[i], v_pools[i], k_sides[i],
                        v_sides[i], entry_lengths, lengths,
                        step_index)
                else:
                    attn_out, k_s, v_s = _slot_attention_block(
                        layer, config, normed, cos, sin, k_caches[i],
                        v_caches[i], k_sides[i], v_sides[i],
                        entry_lengths, lengths, step_index)
                new_k.append(k_s)
                new_v.append(v_s)
                return attn_out

            next_tokens = _token_block_argmax(
                params, config, tokens[:, None], attend)[:, 0]
            next_tokens = jnp.where(active, next_tokens, tokens)
            lengths = jnp.where(active, lengths + 1, lengths)
            budgets = jnp.where(active, budgets - 1, budgets)
            still = active & (budgets > 0) & (next_tokens != eos)
            return ((next_tokens, lengths, still, budgets, new_k,
                     new_v), (next_tokens, active))

        (tokens, lengths, active, budgets, k_sides, v_sides), \
            (emitted, emitted_active) = jax.lax.scan(
                body, (tokens, lengths, active, budgets, k_sides,
                       v_sides), jnp.arange(num_steps))

        # merge: each slot's side rows land at their absolute positions
        # [entry_length, entry_length + num_steps) — rows past a slot's
        # actual take are dead cells in blocks it owns, same invariant
        # as the dense merge's garbage rows.  Slots inactive at round
        # entry drop entirely (their stale lengths point into prompt
        # regions their extends are writing).
        positions = entry_lengths[:, None] + jnp.arange(num_steps)[None]
        live = entry_active[:, None]
        k_pools = _paged_scatter(k_pools, tables, positions, live,
                                 k_sides, isinstance(k_pools[0], dict),
                                 block_tokens)
        v_pools = _paged_scatter(v_pools, tables, positions, live,
                                 v_sides, isinstance(v_pools[0], dict),
                                 block_tokens)
        return (emitted, emitted_active, tokens, lengths,
                k_pools, v_pools)

    return jax.jit(step, static_argnames=("num_steps", "eos", "t_cap"),
                   donate_argnames=("k_pools", "v_pools"))


@functools.lru_cache(maxsize=16)
def _paged_step_for(config: LlamaConfig, kernel: bool = False):
    """Process-wide builder cache, like serving._step_for.  Keyed on
    the kernel toggle so the pallas variant and the gather oracle
    coexist in one process (parity tests, ab_decode_attention)."""
    return _build_paged_step(config, kernel)


def _build_paged_spec_step(config: LlamaConfig, k_spec: int,
                           ngram: int, kernel: bool = False):
    """Paged sibling of serving._build_spec_step: the drafting /
    widened verify / acceptance scan body is the SAME object
    (serving._spec_scan_body — shared like _slot_attention_spec and
    _token_block_argmax so the numerics cannot drift) over gathered
    pool views; the round's consumed side entries scatter-merge to
    (block, offset) pairs, rejected drafts dropping via their
    _POS_INVALID positions exactly as the dense merge drops them.

    kernel=True routes the scan body's attention seam to the fused
    pallas kernel (_kernel_attention_spec over the pool leaves +
    table) — the verify stays widened INSIDE the one kernel, so spec
    mode needs no second pallas variant."""
    from .serving import _POS_INVALID, _spec_scan_body
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)
    width = k_spec + 1

    def spec_step(params, tokens, lengths, active, budgets, context,
                  k_pools, v_pools, tables, num_steps, eos, t_cap):
        block_tokens = \
            jax.tree_util.tree_leaves(k_pools[0])[0].shape[2]
        if kernel:
            k_caches, v_caches = k_pools, v_pools
            attention = functools.partial(
                _kernel_attention_spec,
                _table_cap(tables, block_tokens, t_cap))
        else:
            k_caches = _gather_views(k_pools, tables, t_cap)
            v_caches = _gather_views(v_pools, tables, t_cap)
            attention = None
        entry_lengths = lengths
        slots_n = tokens.shape[0]
        side_len = num_steps * width
        side_shape = (slots_n, config.num_kv_heads, side_len,
                      config.head_dim)
        k_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        v_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        pos_side = jnp.full((slots_n, side_len), _POS_INVALID,
                            jnp.int32)
        body = _spec_scan_body(config, cos, sin, k_spec, ngram,
                               params, eos, k_caches, v_caches,
                               entry_lengths, attention=attention)

        (tokens, lengths, active, budgets, context, k_sides, v_sides,
         pos_side), (emitted, emit_mask) = jax.lax.scan(
            body, (tokens, lengths, active, budgets, context, k_sides,
                   v_sides, pos_side), jnp.arange(num_steps))

        live = pos_side < _POS_INVALID
        k_pools = _paged_scatter(k_pools, tables, pos_side, live,
                                 k_sides, isinstance(k_pools[0], dict),
                                 block_tokens)
        v_pools = _paged_scatter(v_pools, tables, pos_side, live,
                                 v_sides, isinstance(v_pools[0], dict),
                                 block_tokens)
        return (emitted, emit_mask, tokens, lengths, context,
                k_pools, v_pools)

    return jax.jit(spec_step,
                   static_argnames=("num_steps", "eos", "t_cap"),
                   donate_argnames=("context", "k_pools", "v_pools"))


@functools.lru_cache(maxsize=16)
def _paged_spec_step_for(config: LlamaConfig, k_spec: int, ngram: int,
                         kernel: bool = False):
    return _build_paged_spec_step(config, k_spec, ngram, kernel)


@functools.lru_cache(maxsize=64)
def _paged_admit_fn_for(config: LlamaConfig, bucket: int, width: int,
                        kv_int8: bool, speculative: bool):
    """Paged sibling of serving._admit_fn_for: the SAME stacked prefill
    compute, but the K/V prefixes scatter into pool blocks named by
    each row's table slice instead of dense slot rows.  Positions past
    a prompt's bucket pad to the block boundary as dead cells in blocks
    the slot owns; invalid (pad) rows carry out-of-range ids and
    drop."""
    from .models.llama import init_llama_caches, llama_hidden

    def admit(params, k_pools, v_pools, tokens, lengths, context,
              prompts, true_lens, slots, valid, tables_rows):
        block_tokens = \
            jax.tree_util.tree_leaves(k_pools[0])[0].shape[2]
        num_total = \
            jax.tree_util.tree_leaves(k_pools[0])[0].shape[0]
        caches = init_llama_caches(config, width, bucket)
        hidden, caches = llama_hidden(params, config, prompts, caches)
        idx = jnp.maximum(true_lens - 1, 0)
        last_hidden = jnp.take_along_axis(
            hidden, idx[:, None, None], axis=1)[:, 0]
        last = L.linear_logits(params["lm_head"], last_hidden)
        firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
        nbb = tables_rows.shape[1]
        padded_t = nbb * block_tokens
        dest = jnp.where(valid[:, None], tables_rows, num_total)
        pad = padded_t - bucket
        for i, cache in enumerate(caches):
            k_rows, v_rows = cache["k"], cache["v"]
            if pad:
                spec = [(0, 0), (0, 0), (0, pad), (0, 0)]
                k_rows = jnp.pad(k_rows, spec)
                v_rows = jnp.pad(v_rows, spec)
            if kv_int8:
                k_rows = L.quantize_kv_cache(k_rows)
                v_rows = L.quantize_kv_cache(v_rows)
            k_pools[i] = L.write_paged_blocks(k_pools[i], dest, k_rows)
            v_pools[i] = L.write_paged_blocks(v_pools[i], dest, v_rows)
        tokens = tokens.at[slots].set(
            jnp.where(valid, firsts, tokens[slots]))
        lengths = lengths.at[slots].set(
            jnp.where(valid, true_lens, lengths[slots]))
        if speculative:
            context = context.at[slots, :bucket].set(
                jnp.where(valid[:, None], prompts,
                          context[slots][:, :bucket]))
        return firsts, k_pools, v_pools, tokens, lengths, context

    return jax.jit(
        admit, donate_argnames=("k_pools", "v_pools", "tokens",
                                "lengths", "context"))


@functools.lru_cache(maxsize=64)
def _paged_extend_fn_for(config: LlamaConfig, chunk_len: int,
                         width: int, kv_int8: bool, speculative: bool,
                         kernel: bool = False):
    """Paged sibling of serving._extend_fn_for: the prefix reads come
    from a gathered pool view (sliced to the dense t_cap so the
    attention shapes — and therefore the greedy numerics — match the
    dense program exactly), and only the chunk's positions scatter
    back.  int8 prefixes dequantize for the attention read and the
    chunk stores quantized, exactly like dense — untouched positions
    are never re-rounded because they are never rewritten at all.

    kernel=True reads the prefix through the pallas kernel instead of
    gathering: the chunk's own K/V ride as the kernel's side buffer
    with a causal triangle mask (the chunk must NOT round-trip through
    the pool before attention — the oracle attends the exact compute-
    dtype rows, then stores quantized), the prefix mask is t < offset
    (positions the pool actually owns; the chunk covers [offset,
    offset + chunk)), and int8 prefixes dequantize INSIDE the kernel
    (fold_scales=False) to match the oracle's dequantize-then-dot
    numerics bit-for-bit."""
    cos, sin = L.rope_frequencies(config.head_dim,
                                  config.max_seq_len,
                                  config.rope_theta)
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    group = num_heads // num_kv

    def extend(params, k_pools, v_pools, tokens, lengths, context,
               chunk_tokens, offsets, slots, valid, finish,
               final_idx, tables_rows, t_cap):
        block_tokens = \
            jax.tree_util.tree_leaves(k_pools[0])[0].shape[2]
        num_total = \
            jax.tree_util.tree_leaves(k_pools[0])[0].shape[0]
        x = L.embedding(params["embed"],
                        chunk_tokens).astype(config.dtype)
        q_pos = offsets[:, None] + jnp.arange(chunk_len)[None, :]
        mask = (jnp.arange(t_cap)[None, None, :] <=
                q_pos[:, :, None])[:, None, None]
        scale = 1.0 / jnp.sqrt(jnp.asarray(config.head_dim,
                                           jnp.float32))
        if kernel:
            cap_tables = _table_cap(tables_rows, block_tokens, t_cap)
            # per-query chunk causality: side position p is visible to
            # chunk query c iff p <= c (both offset-relative)
            tri = jnp.broadcast_to(
                jnp.tril(jnp.ones((chunk_len, chunk_len), bool))[None],
                (x.shape[0], chunk_len, chunk_len))
        nbt = tables_rows.shape[1]
        blocks = q_pos // block_tokens
        block_offsets = q_pos % block_tokens
        dest = jnp.take_along_axis(tables_rows,
                                   jnp.clip(blocks, 0, nbt - 1),
                                   axis=1)
        dest = jnp.where(valid[:, None] & (blocks < nbt), dest,
                         num_total)

        def write_rows(rows, chunk_kv, offs):
            return jax.vmap(
                lambda row, kv, off: jax.lax.dynamic_update_slice(
                    row, kv, (0, off, 0)))(rows, chunk_kv, offs)

        for i, layer in enumerate(params["layers"]):
            normed = L.rms_norm(layer["ln_attn"], x)
            q = L._split_heads(L.linear(layer["attn"]["q"], normed),
                               num_heads)
            k = L._split_heads(L.linear(layer["attn"]["k"], normed),
                               num_kv)
            v = L._split_heads(L.linear(layer["attn"]["v"], normed),
                               num_kv)
            q = L.apply_rope(q, cos, sin, offsets)
            k = L.apply_rope(k, cos, sin, offsets)
            if kernel:
                from .ops.paged_attention import \
                    paged_decode_attention
                q_grouped = q.reshape(q.shape[0], num_kv,
                                      group * chunk_len,
                                      config.head_dim)
                out = paged_decode_attention(
                    q_grouped, k_pools[i], v_pools[i], cap_tables,
                    k, v, tri, offsets, groups=group,
                    fold_scales=False)
                out = out.reshape(out.shape[0], num_heads, chunk_len,
                                  config.head_dim).astype(x.dtype)
            else:
                gathered_k = _slice_time(
                    L.gather_paged_kv(k_pools[i], tables_rows), t_cap)
                gathered_v = _slice_time(
                    L.gather_paged_kv(v_pools[i], tables_rows), t_cap)
                if kv_int8:
                    k_rows = write_rows(
                        L.dequantize_kv_cache(gathered_k, x.dtype), k,
                        offsets)
                    v_rows = write_rows(
                        L.dequantize_kv_cache(gathered_v, x.dtype), v,
                        offsets)
                else:
                    k_rows = write_rows(gathered_k, k, offsets)
                    v_rows = write_rows(gathered_v, v, offsets)
                q_grouped = q.reshape(q.shape[0], num_kv, group,
                                      chunk_len, config.head_dim)
                scores = jnp.einsum(
                    "akgcd,aktd->akgct", q_grouped, k_rows,
                    preferred_element_type=jnp.float32) * scale
                scores = jnp.where(mask, scores, -1e30)
                weights = jax.nn.softmax(
                    scores, axis=-1).astype(v_rows.dtype)
                out = jnp.einsum("akgct,aktd->akgcd", weights, v_rows,
                                 preferred_element_type=jnp.float32)
                out = out.reshape(out.shape[0], num_heads, chunk_len,
                                  config.head_dim).astype(x.dtype)
            x = x + L.linear(layer["attn"]["o"], L._merge_heads(out))
            x = x + llama_ffn(layer, config,
                              L.rms_norm(layer["ln_mlp"], x))
            if kv_int8:
                k_store = L.quantize_kv_cache(k)
                v_store = L.quantize_kv_cache(v)
            else:
                k_store, v_store = k, v
            k_pools[i] = L.scatter_paged_rows(k_pools[i], dest,
                                              block_offsets, k_store)
            v_pools[i] = L.scatter_paged_rows(v_pools[i], dest,
                                              block_offsets, v_store)
        x = L.rms_norm(params["ln_out"], x)
        last_hidden = jnp.take_along_axis(
            x, final_idx[:, None, None], axis=1)[:, 0]
        last = L.linear_logits(params["lm_head"], last_hidden)
        firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
        apply = valid & finish
        tokens = tokens.at[slots].set(
            jnp.where(apply, firsts, tokens[slots]))
        lengths = lengths.at[slots].set(
            jnp.where(apply, offsets + final_idx + 1,
                      lengths[slots]))
        if speculative:
            ctx_rows = context[slots]
            written = jax.vmap(
                lambda row, blk, off: jax.lax.dynamic_update_slice(
                    row, blk, (off,)))(ctx_rows, chunk_tokens,
                                       offsets)
            context = context.at[slots].set(
                jnp.where(valid[:, None], written, ctx_rows))
        return firsts, k_pools, v_pools, tokens, lengths, context

    return jax.jit(
        extend, static_argnames=("t_cap",),
        donate_argnames=("k_pools", "v_pools", "tokens", "lengths",
                         "context"))


@functools.lru_cache(maxsize=64)
def _paged_ctx_fn_for(t_write: int):
    """Speculative-context seed for a paged prefix-hit admit: the KV
    aliasing is a pure host-side table edit, but the drafter's history
    buffer still needs the cached prompt tokens — the only device write
    a paged hit pays (and only with speculation on)."""

    def seed(context, slot, ctx_tokens):
        return context.at[slot, :t_write].set(ctx_tokens)

    return jax.jit(seed, donate_argnames=("context",))
