# Service layer: discoverable, addressable endpoints.
#
# Capability parity with the reference service layer
# (reference: aiko_services/service.py:105-569): versioned protocol URIs,
# the discovery record (topic_path, name, protocol, transport, owner, tags),
# wildcard filters, tag matching, the two-level Services collection, and the
# Service base that registers itself with its process runtime and derives
# its control/in/log/out/state topics.
#
# Design change: services are plain classes wired by constructor injection —
# no interface/implementation weaver (the reference's "Frankenstein"
# composition engine, component.py:50-219, exists to emulate exactly this).

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = [
    "ServiceProtocol", "ServiceFields", "ServiceFilter", "ServiceTags",
    "ServiceTopicPath", "Services", "Service",
    "PROTOCOL_PREFIX", "SERVICE_PROTOCOL_VERSION",
]

# Protocol URIs identify what a service speaks, independent of its name.
PROTOCOL_PREFIX = "aiko_tpu/protocol"
SERVICE_PROTOCOL_VERSION = "0"


class ServiceProtocol:
    def __init__(self, name: str, version: str = SERVICE_PROTOCOL_VERSION,
                 prefix: str = PROTOCOL_PREFIX):
        self.name = name
        self.version = version
        self.prefix = prefix

    def __str__(self):
        return f"{self.prefix}/{self.name}:{self.version}"

    @staticmethod
    def name_of(protocol_uri: str) -> str:
        return protocol_uri.rsplit("/", 1)[-1].split(":")[0]


class ServiceTags:
    """Tags are "key=value" strings on the discovery record."""

    @staticmethod
    def to_dict(tags) -> dict:
        out = {}
        for tag in tags or ():
            if "=" in tag:
                k, v = tag.split("=", 1)
                out[k] = v
        return out

    @staticmethod
    def match(tags, required) -> bool:
        """True when every tag in `required` appears in `tags` ("*" = any)."""
        if required in ("*", None) or not required:
            return True
        have = set(tags or ())
        return all(tag in have for tag in required)


class ServiceTopicPath:
    """{namespace}/{hostname}/{process_id}/{service_id}"""

    def __init__(self, namespace, hostname, process_id, service_id):
        self.namespace = namespace
        self.hostname = hostname
        self.process_id = str(process_id)
        self.service_id = str(service_id)

    @classmethod
    def parse(cls, topic_path: str):
        parts = topic_path.split("/")
        if len(parts) == 4:
            return cls(*parts)
        if len(parts) == 3:
            return cls(parts[0], parts[1], parts[2], "0")
        return None

    @property
    def process_path(self) -> str:
        return f"{self.namespace}/{self.hostname}/{self.process_id}"

    def terse(self) -> str:
        return f"{self.hostname}:{self.process_id}.{self.service_id}"

    def __str__(self):
        return f"{self.process_path}/{self.service_id}"


@dataclass
class ServiceFields:
    """The discovery record the registrar stores per service."""
    topic_path: str
    name: str
    protocol: str
    transport: str = "memory"
    owner: str = ""
    tags: list = field(default_factory=list)

    def to_record(self) -> list:
        return [self.topic_path, self.name, self.protocol,
                self.transport, self.owner, list(self.tags)]

    @classmethod
    def from_record(cls, record):
        topic_path, name, protocol, transport, owner = record[:5]
        tags = record[5] if len(record) > 5 else []
        if isinstance(tags, str):
            tags = [tags]
        return cls(topic_path, name, protocol, transport, owner, list(tags))


@dataclass
class ServiceFilter:
    """Wildcard filter over discovery records ("*" matches anything)."""
    topic_paths: object = "*"     # "*" or list of topic paths
    name: str = "*"
    protocol: str = "*"
    transport: str = "*"
    owner: str = "*"
    tags: object = "*"            # "*" or list of required "k=v" tags

    def matches(self, fields: ServiceFields) -> bool:
        if self.topic_paths != "*" and \
                fields.topic_path not in self.topic_paths:
            return False
        if self.name != "*" and fields.name != self.name:
            return False
        if self.protocol != "*":
            if self.protocol.endswith("*"):
                if not fields.protocol.startswith(self.protocol[:-1]):
                    return False
            elif fields.protocol != self.protocol:
                return False
        if self.transport != "*" and fields.transport != self.transport:
            return False
        if self.owner != "*" and fields.owner != self.owner:
            return False
        return ServiceTags.match(fields.tags, self.tags)


class Services:
    """Two-level map: process topic path → service topic path → fields."""

    def __init__(self):
        self._processes: dict[str, dict[str, ServiceFields]] = {}

    def add(self, fields: ServiceFields) -> None:
        tp = ServiceTopicPath.parse(fields.topic_path)
        if tp is None:
            return
        self._processes.setdefault(tp.process_path, {})[
            fields.topic_path] = fields

    def remove(self, topic_path: str) -> ServiceFields | None:
        tp = ServiceTopicPath.parse(topic_path)
        if tp is None:
            return None
        process = self._processes.get(tp.process_path)
        if not process:
            return None
        fields = process.pop(topic_path, None)
        if not process:
            self._processes.pop(tp.process_path, None)
        return fields

    def remove_process(self, process_path: str) -> list[ServiceFields]:
        process = self._processes.pop(process_path, None)
        return list(process.values()) if process else []

    def get(self, topic_path: str) -> ServiceFields | None:
        tp = ServiceTopicPath.parse(topic_path)
        if tp is None:
            return None
        return self._processes.get(tp.process_path, {}).get(topic_path)

    def filter(self, service_filter: ServiceFilter) -> list[ServiceFields]:
        return [f for f in self if service_filter.matches(f)]

    def __iter__(self):
        for process in list(self._processes.values()):
            yield from list(process.values())

    def __len__(self):
        return sum(len(p) for p in self._processes.values())

    def count_processes(self) -> int:
        return len(self._processes)


class Service:
    """A discoverable endpoint.  Subclasses implement behaviour; the
    constructor registers with the process runtime, which assigns the
    service_id and wires topic routing."""

    def __init__(self, runtime, name: str,
                 protocol: ServiceProtocol | str | None = None,
                 tags=None, owner: str | None = None):
        self.runtime = runtime
        self.name = name
        self.protocol = str(protocol) if protocol else \
            str(ServiceProtocol("service"))
        self.tags = list(tags or [])
        self.owner = owner if owner is not None else runtime.username
        self.service_id = runtime.add_service(self)
        self.topic_path = f"{runtime.topic_path}/{self.service_id}"

    # per-service topics (reference: service.py:539-543)
    @property
    def topic_control(self):
        return f"{self.topic_path}/control"

    @property
    def topic_in(self):
        return f"{self.topic_path}/in"

    @property
    def topic_log(self):
        return f"{self.topic_path}/log"

    @property
    def topic_out(self):
        return f"{self.topic_path}/out"

    @property
    def topic_state(self):
        return f"{self.topic_path}/state"

    def service_fields(self) -> ServiceFields:
        return ServiceFields(
            topic_path=self.topic_path, name=self.name,
            protocol=self.protocol, transport=self.runtime.transport_name,
            owner=self.owner, tags=self.tags)

    def add_tags(self, tags) -> None:
        for tag in tags:
            if tag not in self.tags:
                self.tags.append(tag)

    def stop(self) -> None:
        """Deregister from the runtime."""
        self.runtime.remove_service(self.service_id)
