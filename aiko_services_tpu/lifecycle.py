# LifeCycleManager / LifeCycleClient: spawn a fleet of worker processes and
# track their health.
#
# Capability parity with the reference lifecycle subsystem
# (reference: aiko_services/lifecycle.py:98-288, :355-388):
#   * the manager spawns N clients (via a spawner callable — OS processes
#     through ProcessManager, or in-process runtimes in tests/TPU pools);
#   * each client calls back `(add_client topic_path id)` on the manager's
#     control topic within a handshake lease (30 s default);
#   * the manager EC-consumes each client's share to watch its lifecycle
#     state, and purges clients the registrar reports gone;
#   * deletion leases force-kill stragglers.
#
# TPU-native addition: the same manager places *device workloads* — a
# client's "process" may be a TPU slice runtime rather than an OS process
# (SURVEY.md §2: elastic scheduling → device/slice placement).

from __future__ import annotations

from dataclasses import dataclass, field

from .actor import Actor
from .lease import Lease
from .process import STATE_ABSENT
from .process_manager import RestartPolicy, RestartWindow
from .service import ServiceProtocol, ServiceTopicPath
from .share import ECConsumer
from .utils import get_logger, parse


def state_topic_of(service_topic_path: str) -> str:
    """The process-liveness topic (service 0's state, where the LWT
    fires) for any service topic path; "" when unparseable."""
    parsed = ServiceTopicPath.parse(service_topic_path)
    return f"{parsed.process_path}/0/state" if parsed else ""


def is_absent(payload) -> bool:
    """True for the process-death payload (STATE_ABSENT contract)."""
    try:
        command, _ = parse(str(payload))
    except Exception:
        return False
    return command == STATE_ABSENT.strip("()")

__all__ = ["LifeCycleManager", "LifeCycleClient",
           "PROTOCOL_LIFECYCLE_MANAGER", "PROTOCOL_LIFECYCLE_CLIENT"]

PROTOCOL_LIFECYCLE_MANAGER = ServiceProtocol("lifecycle_manager")
PROTOCOL_LIFECYCLE_CLIENT = ServiceProtocol("lifecycle_client")
_HANDSHAKE_LEASE = 30.0     # seconds (reference: lifecycle.py:74)
_DELETION_LEASE = 30.0      # seconds (reference: lifecycle.py:75)


@dataclass
class _ClientRecord:
    client_id: str
    topic_path: str = ""
    state: str = "spawned"          # spawned | ready | deleting | gone
    lease: Lease | None = None
    consumer: ECConsumer | None = None
    share: dict = field(default_factory=dict)
    state_topic: str = ""           # client process LWT topic (crash watch)


class LifeCycleManager(Actor):
    """Spawns clients via `spawner(client_id, manager_topic_path)` and
    tracks them.  spawner returns an opaque handle passed to
    `terminator(client_id, handle)` on deletion (both injectable: OS
    processes, in-process runtimes, TPU slice allocations)."""

    def __init__(self, runtime, name: str, spawner, terminator=None,
                 client_change_handler=None,
                 handshake_lease_time: float = _HANDSHAKE_LEASE,
                 restart_policy: RestartPolicy | None = None):
        super().__init__(runtime, name, PROTOCOL_LIFECYCLE_MANAGER)
        self.logger = get_logger(f"lifecycle_manager.{name}")
        self.spawner = spawner
        self.terminator = terminator
        self.client_change_handler = client_change_handler
        self.handshake_lease_time = handshake_lease_time
        # restart_policy supervises the FLEET: a client that dies (LWT)
        # is replaced under backoff; too many deaths inside the policy
        # window is a crash loop and replacement stops (ISSUE 4)
        self.restart_policy = restart_policy
        self.crash_looping = False
        self._restart_window = RestartWindow(restart_policy) \
            if restart_policy else None
        self._restart_timers: set[int] = set()
        self.restart_stats = {"respawns": 0, "deaths": 0}
        self.clients: dict[str, _ClientRecord] = {}
        self._handles: dict[str, object] = {}
        self._counter = 0
        # crash watch refcounts: several clients may share one process,
        # so the state-topic handler lives until the LAST of them goes
        self._state_watch: dict[str, set] = {}    # topic -> client_ids
        runtime.add_message_handler(self._control_handler,
                                    self.topic_control)
        self.ec_producer.update("client_count", 0)

    # -- spawning ----------------------------------------------------------
    def create_clients(self, count: int) -> list[str]:
        ids = []
        for _ in range(count):
            client_id = str(self._counter)
            self._counter += 1
            record = _ClientRecord(client_id)
            record.lease = Lease(
                self.runtime.event, self.handshake_lease_time, client_id,
                lease_expired_handler=self._handshake_expired)
            self.clients[client_id] = record
            self._handles[client_id] = self.spawner(client_id,
                                                    self.topic_path)
            ids.append(client_id)
        self._publish_count()
        return ids

    def _handshake_expired(self, client_id) -> None:
        record = self.clients.get(str(client_id))
        if record and record.state == "spawned":
            self.logger.warning("client %s missed handshake; deleting",
                                client_id)
            self.delete_client(str(client_id))

    # -- protocol ----------------------------------------------------------
    def _control_handler(self, _topic, payload) -> None:
        from .utils import parse
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "add_client" and len(params) >= 2:
            self._add_client(params[0], str(params[1]))

    def _add_client(self, topic_path: str, client_id: str) -> None:
        record = self.clients.get(client_id)
        if record is None or record.state != "spawned":
            return
        record.topic_path = topic_path
        record.state = "ready"
        if record.lease:
            record.lease.terminate()
            record.lease = None
        # mirror the client's share (lifecycle state etc.)
        record.consumer = ECConsumer(
            self.runtime, record.share, f"{topic_path}/control")
        # crash detection: the client process's LWT (reference watches
        # registrar removals, lifecycle.py:190-227; watching the state
        # topic directly needs no registrar in the loop)
        record.state_topic = state_topic_of(topic_path)
        if record.state_topic:
            watchers = self._state_watch.setdefault(record.state_topic,
                                                    set())
            if not watchers:
                self.runtime.add_message_handler(
                    self._client_state_handler, record.state_topic)
            watchers.add(client_id)
        self.logger.info("client %s ready at %s", client_id, topic_path)
        if self.client_change_handler:
            self.client_change_handler("add", client_id, record)
        self._publish_count()

    def _client_state_handler(self, topic, payload) -> None:
        if not is_absent(payload):
            return
        died = 0
        for client_id, record in list(self.clients.items()):
            if record.state_topic == topic:
                self.logger.warning("client %s died (LWT on %s)",
                                    client_id, topic)
                died += 1
                self.delete_client(client_id)
        for _ in range(died):
            self._client_died()

    # -- supervised replacement (ISSUE 4) -----------------------------------
    def _client_died(self) -> None:
        if self._restart_window is None or self.crash_looping:
            return
        self.restart_stats["deaths"] += 1
        delay = self._restart_window.record(
            self.runtime.event.clock.now())
        if delay is None:
            self.crash_looping = True
            self.logger.error(
                "lifecycle %s: client crash loop (%d deaths in %.1fs); "
                "no further replacements", self.name,
                len(self._restart_window.events),
                self.restart_policy.window)
            if self.client_change_handler:
                self.client_change_handler("crash_loop", "", None)
            return
        self.logger.warning(
            "lifecycle %s: replacing dead client in %.2fs "
            "(death %d/%d in window)", self.name, delay,
            len(self._restart_window.events),
            self.restart_policy.max_restarts)
        handle_box = []

        def respawn():
            self._restart_timers.discard(handle_box[0])
            if not self.crash_looping:
                self.restart_stats["respawns"] += 1
                self.create_clients(1)

        # each death queues exactly one replacement; every pending
        # handle is tracked so stop() cancels them all
        handle_box.append(
            self.runtime.event.add_oneshot_handler(respawn, delay))
        self._restart_timers.add(handle_box[0])

    def _unwatch_state(self, topic: str, client_id: str) -> None:
        watchers = self._state_watch.get(topic)
        if watchers is None:
            return
        watchers.discard(client_id)
        if not watchers:
            del self._state_watch[topic]
            self.runtime.remove_message_handler(self._client_state_handler,
                                                topic)

    # -- deletion ----------------------------------------------------------
    def delete_client(self, client_id: str,
                      drain_s: float | None = None) -> None:
        """Retire one client.  Default: polite `(control_stop)` now,
        deletion lease force-kills stragglers.  With `drain_s`
        (ISSUE 19) the retirement routes through graceful drain
        instead of kill: the client gets `(control_drain drain_s)` —
        a serving actor winds its decoder down, migrates session KV,
        then stops itself — and only a Lease at the HARD deadline
        falls back to the stop/terminate crash path.  Either way the
        record pops NOW: the client's eventual LWT must read as a
        planned exit, never as a death the restart policy respawns."""
        record = self.clients.pop(str(client_id), None)
        if record is None:
            return
        record.state = "deleting"
        if record.lease:
            record.lease.terminate()
        if record.consumer:
            record.consumer.terminate()
        if record.state_topic:
            self._unwatch_state(record.state_topic, str(client_id))
        drain = drain_s is not None and drain_s > 0 \
            and bool(record.topic_path)
        if record.topic_path:
            if drain:
                self.runtime.publish(f"{record.topic_path}/in",
                                     f"(control_drain {drain_s})")
                # the hard deadline: a client that did not finish its
                # drain inside the window gets the crash path after all
                Lease(self.runtime.event, float(drain_s), client_id,
                      lease_expired_handler=lambda cid,
                      topic=record.topic_path:
                          self.runtime.publish(f"{topic}/in",
                                               "(control_stop)"))
            else:
                # polite ask first; the deletion lease force-kills
                # stragglers
                self.runtime.publish(f"{record.topic_path}/in",
                                     "(control_stop)")
        handle = self._handles.pop(str(client_id), None)
        if self.terminator:
            grace = (float(drain_s) if drain else 0.0) + _DELETION_LEASE
            Lease(self.runtime.event, grace, client_id,
                  lease_expired_handler=lambda cid, h=handle:
                      self.terminator(str(cid), h))
        if self.client_change_handler:
            self.client_change_handler("remove", str(client_id), record)
        self._publish_count()

    def delete_all(self) -> None:
        for client_id in list(self.clients):
            self.delete_client(client_id)

    def ready_count(self) -> int:
        return sum(1 for r in self.clients.values() if r.state == "ready")

    def ready_ids(self) -> list[str]:
        """Ready client ids in creation order (ids are monotonic)."""
        return sorted((cid for cid, record in self.clients.items()
                       if record.state == "ready"), key=int)

    # -- elastic capacity (ISSUE 9: the autoscaler's actuator) --------------
    def scale_to(self, count: int, drain_s: float | None = None) -> int:
        """Grow or shrink the fleet to `count` clients.  Growth spawns
        through the normal create path (handshake-leased, supervised
        under the restart policy); shrink retires the NEWEST ready
        clients first — the oldest capacity is the warmest (compiled
        programs, filled caches), so it is the last to go.  With
        `drain_s` (ISSUE 19) each retirement routes through graceful
        drain (see delete_client) instead of an immediate stop.
        Returns the signed delta actually applied."""
        count = max(0, int(count))
        current = len(self.clients)
        if count > current:
            self.create_clients(count - current)
            return count - current
        removed = 0
        for client_id in reversed(self.ready_ids()):
            if current - removed <= count:
                break
            self.delete_client(client_id, drain_s=drain_s)
            removed += 1
        return -removed

    def _publish_count(self) -> None:
        self.ec_producer.update("client_count", len(self.clients))

    def stop(self) -> None:
        for handle in self._restart_timers:
            self.runtime.event.remove_timer_handler(handle)
        self._restart_timers.clear()
        for record in self.clients.values():
            if record.lease:
                record.lease.terminate()
            if record.consumer:
                record.consumer.terminate()
        for topic in list(self._state_watch):
            self.runtime.remove_message_handler(self._client_state_handler,
                                                topic)
        self._state_watch.clear()
        self.runtime.remove_message_handler(self._control_handler,
                                            self.topic_control)
        super().stop()


class LifeCycleClient(Actor):
    """Worker-side half: announces itself to the manager's control topic
    on creation (reference: lifecycle.py:355-388)."""

    def __init__(self, runtime, name: str, manager_topic_path: str,
                 client_id: str, protocol=None):
        super().__init__(runtime, name,
                         protocol or PROTOCOL_LIFECYCLE_CLIENT)
        self.client_id = client_id
        self.manager_topic_path = manager_topic_path
        self.ec_producer.update("client_id", client_id)
        from .utils import generate
        runtime.publish(f"{manager_topic_path}/control",
                        generate("add_client",
                                 [self.topic_path, client_id]))
