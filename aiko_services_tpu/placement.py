# Device placement: the TPU pod as an allocatable pool behind the
# lifecycle manager.
#
# SURVEY.md §2 "elastic scheduling → device placement": the reference's
# LifeCycleManager spawns OS processes (reference: aiko_services/
# lifecycle.py:144-288) with no notion of accelerators.  Here the same
# spawn/handshake/lease machinery places *device workloads*: a DevicePool
# partitions the slice's chips, each spawned client receives a
# DeviceSlice (device ids + mesh geometry) it builds its ComputeRuntime
# over, and the manager EC-shares pool occupancy and per-client
# placement so dashboards see device health next to process health
# (SURVEY.md §7 "two-plane consistency": discovery/liveness must track
# device health, not just processes).

from __future__ import annotations

import math
from dataclasses import dataclass

from .lifecycle import LifeCycleManager, is_absent
from .parallel.mesh import MeshSpec, create_mesh
from .utils import get_logger

__all__ = ["DeviceSlice", "DevicePool", "PlacementManager",
           "report_compute"]


@dataclass
class DeviceSlice:
    """A contiguous run of devices plus the mesh geometry to lay over
    them.  Contiguity is deliberate: neighbouring TPU chips share the
    fastest ICI links, so model/TP axes stay on-wire-adjacent."""
    owner: str
    devices: list
    mesh_axes: dict                     # resolved axis name -> size

    @property
    def device_ids(self) -> list:
        return [d.id for d in self.devices]

    def mesh(self):
        """Build the jax Mesh for this slice (axes resolved already)."""
        return create_mesh(self.mesh_axes, self.devices)

    def describe(self) -> str:
        axes = ",".join(f"{k}={v}" for k, v in self.mesh_axes.items())
        return f"devices={self.device_ids} mesh=({axes})"


class DevicePool:
    """Allocator over the process-visible device inventory.

    Slices are handed out as contiguous runs (first-fit) and returned by
    owner; double-allocation of a chip is impossible by construction."""

    def __init__(self, devices=None):
        if devices is None:
            import jax
            devices = jax.devices()
        self.devices = list(devices)
        self._owned: dict[str, DeviceSlice] = {}      # owner -> slice

    # -- queries -----------------------------------------------------------
    @property
    def total(self) -> int:
        return len(self.devices)

    @property
    def allocated(self) -> int:
        return sum(len(s.devices) for s in self._owned.values())

    @property
    def free(self) -> int:
        return self.total - self.allocated

    def slice_of(self, owner: str) -> DeviceSlice | None:
        return self._owned.get(owner)

    def occupancy(self) -> dict:
        """owner -> device id list (EC-share friendly)."""
        return {owner: s.device_ids for owner, s in self._owned.items()}

    # -- allocate / release ------------------------------------------------
    def allocate(self, mesh_axes: dict | int, owner: str) -> DeviceSlice:
        """mesh_axes: axis dict ({"data": 2, "model": 2}) or a plain
        device count (1D data mesh).  Raises when owner already holds a
        slice or no contiguous run fits."""
        if owner in self._owned:
            raise ValueError(f"{owner!r} already holds "
                             f"{self._owned[owner].describe()}")
        if isinstance(mesh_axes, int):
            mesh_axes = {"data": mesh_axes}
        count = MeshSpec(dict(mesh_axes))
        if -1 in mesh_axes.values():
            # a wildcard axis can only fill what is contiguously
            # OBTAINABLE, not the raw free count (fragmentation)
            longest = self._longest_free_run()
            if longest == 0:
                raise RuntimeError(
                    f"pool exhausted ({self.total} devices allocated)")
            fixed = max(math.prod(
                v for v in mesh_axes.values() if v != -1), 1)
            if longest < fixed:
                raise RuntimeError(
                    f"fragmented pool: longest contiguous free run is "
                    f"{longest} devices but the fixed axes need "
                    f"multiples of {fixed} "
                    f"(free={self.free}/{self.total})")
            resolved = count.resolve(longest - longest % fixed)
        else:
            resolved = count.resolve(math.prod(mesh_axes.values()))
        need = math.prod(resolved.values())
        run = self._find_run(need)
        if run is None:
            raise RuntimeError(
                f"no contiguous run of {need} free devices "
                f"(free={self.free}/{self.total})")
        allocated = DeviceSlice(owner, run, resolved)
        self._owned[owner] = allocated
        return allocated

    def release(self, owner: str) -> bool:
        return self._owned.pop(owner, None) is not None

    def _find_run(self, need: int):
        taken = {id(d) for s in self._owned.values() for d in s.devices}
        run: list = []
        for device in self.devices:
            if id(device) in taken:
                run = []
                continue
            run.append(device)
            if len(run) == need:
                return run
        return None

    def _longest_free_run(self) -> int:
        taken = {id(d) for s in self._owned.values() for d in s.devices}
        longest = current = 0
        for device in self.devices:
            current = 0 if id(device) in taken else current + 1
            longest = max(longest, current)
        return longest


def report_compute(client, compute) -> None:
    """Copy a ComputeRuntime's device identity into a LifeCycleClient's
    EC share: the manager mirrors the CLIENT's share, so this is how a
    worker's device health reaches the manager/dashboard."""
    for key in ("device_count", "platform", "device_kind", "mesh"):
        value = compute.ec_producer.get(key)
        if value is not None:
            client.ec_producer.update(key, value)


class PlacementManager(LifeCycleManager):
    """LifeCycleManager that owns a DevicePool: every client it spawns
    gets a DeviceSlice, and the slice returns to the pool when the
    client dies (handshake miss, registrar removal, or deletion).

    spawner(client_id, manager_topic_path, device_slice) -> handle —
    the extra argument vs the base class; in-process runtimes in tests,
    OS processes (with device ids passed through the environment /
    spawn record) in deployment."""

    def __init__(self, runtime, name: str, spawner, pool: DevicePool,
                 client_mesh_axes: dict | int = 1, terminator=None,
                 **kwargs):
        self.pool = pool
        self.client_mesh_axes = client_mesh_axes
        self._placed_spawner = spawner
        self._user_terminator = terminator
        # state topic -> client_ids whose slices await vacate confirmation
        self._pending_release: dict[str, set] = {}
        super().__init__(runtime, name,
                         spawner=self._spawn_with_placement,
                         terminator=self._terminate_and_release, **kwargs)
        self.logger = get_logger(f"placement_manager.{name}")
        self._publish_pool()

    def _spawn_with_placement(self, client_id: str, topic_path: str):
        device_slice = self.pool.allocate(self.client_mesh_axes, client_id)
        self.ec_producer.update(f"placement.{client_id}",
                                device_slice.describe())
        self._publish_pool()
        try:
            return self._placed_spawner(client_id, topic_path,
                                        device_slice)
        except Exception:
            # spawn failed: the slice must not leak
            self.pool.release(client_id)
            self.ec_producer.remove(f"placement.{client_id}")
            self._publish_pool()
            raise

    def delete_client(self, client_id: str) -> None:
        """The slice is NOT freed here: the chips are only safe to
        re-hand-out once the old client has provably vacated them (TPU
        backends take exclusive device ownership) — even a client that
        missed its handshake may have initialized jax on the slice.
        Release happens on the process's absent/LWT state, or at the
        latest when the deletion lease force-terminates the client."""
        client_id = str(client_id)
        record = self.clients.get(client_id)
        if record is None:
            return              # idempotent: repeat deletes must not
                                # touch slices already parked pending
        state_topic = record.state_topic
        super().delete_client(client_id)
        if self.pool.slice_of(client_id) is None:
            return                       # nothing held
        if state_topic:
            # watch for a FUTURE absent (operator-initiated delete); a
            # crash-driven delete is released by _client_state_handler
            # below, which owns the in-flight absent event
            pending = self._pending_release.setdefault(state_topic, set())
            if not pending:
                self.runtime.add_message_handler(self._release_on_absent,
                                                 state_topic)
            pending.add(client_id)
        # no state topic (never handshook): the always-armed deletion
        # lease (_terminate_and_release) reclaims after force-kill

    def _client_state_handler(self, topic, payload) -> None:
        """Absent arrives → base deletes the clients (parking their
        slices) → release them here, directly off the event: the death
        is confirmed, and waiting for a retained-message redelivery
        would hang when other clients keep the topic subscribed."""
        super()._client_state_handler(topic, payload)
        if is_absent(payload):
            self._release_pending(topic)

    def _release_on_absent(self, topic, payload) -> None:
        if is_absent(payload):
            self._release_pending(topic)

    def _release_pending(self, topic: str) -> None:
        for client_id in self._pending_release.pop(topic, set()):
            self._release(client_id)
        self.runtime.remove_message_handler(self._release_on_absent,
                                            topic)

    def _terminate_and_release(self, client_id: str, handle) -> None:
        """Deletion-lease expiry: force-kill (if the caller supplied a
        terminator) then reclaim — the bounded fallback when no LWT
        ever arrives."""
        if self._user_terminator is not None:
            self._user_terminator(client_id, handle)
        client_id = str(client_id)
        for topic, pending in list(self._pending_release.items()):
            if client_id in pending:
                pending.discard(client_id)
                if not pending:
                    del self._pending_release[topic]
                    self.runtime.remove_message_handler(
                        self._release_on_absent, topic)
        if self.pool.slice_of(client_id) is not None:
            self._release(client_id)

    def _release(self, client_id: str) -> None:
        if self.pool.release(client_id):
            self.ec_producer.remove(f"placement.{client_id}")
            self._publish_pool()

    def device_health(self) -> dict:
        """Aggregate of what ready clients report in their EC shares
        (ComputeRuntime publishes device_count/platform/mesh)."""
        health = {}
        for client_id, record in self.clients.items():
            health[client_id] = {
                "state": record.state,
                "devices": self.pool.slice_of(client_id).device_ids
                if self.pool.slice_of(client_id) else [],
                "reported_device_count":
                    record.share.get("device_count"),
                "platform": record.share.get("platform"),
            }
        return health

    def _publish_pool(self) -> None:
        self.ec_producer.update("devices_total", self.pool.total)
        self.ec_producer.update("devices_free", self.pool.free)
        self.ec_producer.update("devices_allocated", self.pool.allocated)

    def stop(self) -> None:
        for topic in list(self._pending_release):
            self.runtime.remove_message_handler(self._release_on_absent,
                                                topic)
        self._pending_release.clear()
        super().stop()
