# aiko_services_tpu: a TPU-native distributed service and dataflow framework
# with the capabilities of aiko_services (see SURVEY.md for the reference
# analysis).  Control plane (actors, discovery, shares) is pure Python;
# compute plane (models, pipeline elements) is jax/XLA/pallas — imported
# lazily so control-plane-only processes never pay the jax import cost.

__version__ = "0.1.0"

from . import utils                                         # noqa: F401
from . import event                                         # noqa: F401
from .connection import Connection, ConnectionState         # noqa: F401
from .event import EventEngine, RealClock, VirtualClock     # noqa: F401
from .lease import Lease                                    # noqa: F401
from .process import ProcessRuntime                         # noqa: F401
from .service import (                                      # noqa: F401
    Service, ServiceFields, ServiceFilter, ServiceProtocol, ServiceTags,
    ServiceTopicPath, Services,
)
from .state import StateMachine, StateMachineError          # noqa: F401
from .share import ECConsumer, ECProducer, ServicesCache    # noqa: F401
from .actor import (                                        # noqa: F401
    Actor, ActorDiscovery, ActorMessage, get_public_methods,
    get_remote_proxy,
)
from .registrar import Registrar                            # noqa: F401
from .process_manager import ProcessManager, RestartPolicy  # noqa: F401
from .lifecycle import (                                    # noqa: F401
    LifeCycleClient, LifeCycleManager,
)
from .autoscaler import Autoscaler, ScalePolicy             # noqa: F401
from .placement import (                                    # noqa: F401
    DevicePool, DeviceSlice, PlacementManager,
)
from .recorder import Recorder                              # noqa: F401
from .compute import ComputeRuntime                         # noqa: F401
from .storage import (                                      # noqa: F401
    ResponseCollector, Storage, do_command, do_request,
)
from .transport import (                                    # noqa: F401
    ChaosBroker, ChaosMessage, FaultPlan, FaultRule, MemoryBroker,
    MemoryMessage, Message, MQTT_AVAILABLE, default_broker, topic_matches,
)
