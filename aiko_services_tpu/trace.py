# Method tracing: AOP-style interceptors on service objects.
#
# Capability parity with the reference proxy layer
# (reference: aiko_services/proxy.py:39-72 — wrapt-based ProxyAllMethods +
# proxy_trace enter/exit): wraps every public method of an instance with
# an interceptor.  No wrapt dependency; wrapping is per-instance
# (instance attributes shadow class methods) and reversible.
#
# Beyond the reference: TraceCollector records structured spans (name,
# wall time, nesting depth) instead of printing — and it is the LOCAL
# LEAF of the distributed tracing model (observe/tracing.py): finished
# spans also feed the process-wide Tracer, stamped with the ambient
# TraceContext's trace id, so a method call made while serving a remote
# frame shows up in the same Perfetto timeline as the hop that caused
# it (SURVEY.md §5.1: the reference has "no span/trace IDs").

from __future__ import annotations

import functools
import itertools
import threading
import time

from .observe import tracing as _tracing

__all__ = ["trace_all_methods", "untrace", "print_tracer",
           "TraceCollector", "Span"]

_span_ids = itertools.count(1)


class Span:
    __slots__ = ("span_id", "parent_id", "name", "start", "duration",
                 "error")

    def __init__(self, span_id, parent_id, name, start):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.duration = None
        self.error = None

    def __repr__(self):
        ms = f"{self.duration * 1e3:.2f}ms" if self.duration is not None \
            else "…"
        return f"Span({self.name} {ms})"


class TraceCollector:
    """Interceptor that records spans with caller/callee nesting.

    The nesting stack is THREAD-LOCAL: spans recorded concurrently from
    the event-loop thread and a caller thread (e.g. a batching
    scheduler's drive thread resolving a deferred while the engine
    walks the next frame) each nest under their own thread's open span
    — a shared stack would cross-link parents between threads and pop
    the wrong span on exit."""

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self.spans: list[Span] = []         # append-only (GIL-safe)
        self._local = threading.local()

    @property
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def __call__(self, name, method, args, kwargs):
        stack = self._stack
        parent = stack[-1].span_id if stack else None
        span = Span(next(_span_ids), parent, name, self.clock())
        self.spans.append(span)
        stack.append(span)
        try:
            return method(*args, **kwargs)
        except Exception as exc:
            span.error = repr(exc)
            raise
        finally:
            span.duration = self.clock() - span.start
            stack.pop()
            # local leaf of the distributed model: finished spans feed
            # the process Tracer under the ambient trace context
            tracer = _tracing.tracer
            if tracer.enabled:
                tracer.record(
                    f"call:{name}", span.start, span.duration,
                    context=_tracing.current_trace(), cat="method",
                    span_id=_tracing.new_span_id(),
                    args={"error": span.error or ""})


def print_tracer(name, method, args, kwargs):
    """The reference's proxy_trace equivalent: enter/exit prints.
    Deliberately console-bound (it exists to eyeball a live object),
    hence the lint-print waivers."""
    print(f"TRACE enter {name}{args!r}")      # graft: disable=lint-print
    try:
        return method(*args, **kwargs)
    finally:
        print(f"TRACE exit  {name}")          # graft: disable=lint-print


def trace_all_methods(instance, interceptor, only=None) -> list[str]:
    """Wrap every public bound method of `instance` with
    interceptor(name, method, args, kwargs).  Returns the wrapped names.
    `only` restricts to the given method names."""
    import inspect

    wrapped = []
    for name in dir(instance):
        if name.startswith("_"):
            continue
        if only is not None and name not in only:
            continue
        # inspect statically first: plain getattr would EXECUTE property
        # getters (service classes here define several, with side effects)
        static = inspect.getattr_static(instance, name, None)
        if not (inspect.isfunction(static) or inspect.ismethod(static)):
            continue
        method = getattr(instance, name)
        if not callable(method) or not hasattr(method, "__self__"):
            continue

        @functools.wraps(method)
        def wrapper(*args, _name=name, _method=method, **kwargs):
            return interceptor(_name, _method, args, kwargs)

        wrapper.__traced__ = method
        instance.__dict__[name] = wrapper
        wrapped.append(name)
    return wrapped


def untrace(instance) -> None:
    """Remove all trace wrappers installed by trace_all_methods."""
    for name, value in list(instance.__dict__.items()):
        if hasattr(value, "__traced__"):
            del instance.__dict__[name]
