# Method tracing: AOP-style interceptors on service objects.
#
# Capability parity with the reference proxy layer
# (reference: aiko_services/proxy.py:39-72 — wrapt-based ProxyAllMethods +
# proxy_trace enter/exit): wraps every public method of an instance with
# an interceptor.  No wrapt dependency; wrapping is per-instance
# (instance attributes shadow class methods) and reversible.
#
# Beyond the reference: TraceCollector records structured spans (name,
# wall time, nesting depth) instead of printing — feeding the same
# metrics surface the pipeline uses (SURVEY.md §5.1: the reference has
# "no span/trace IDs").

from __future__ import annotations

import functools
import itertools
import time

__all__ = ["trace_all_methods", "untrace", "print_tracer",
           "TraceCollector", "Span"]

_span_ids = itertools.count(1)


class Span:
    __slots__ = ("span_id", "parent_id", "name", "start", "duration",
                 "error")

    def __init__(self, span_id, parent_id, name, start):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.duration = None
        self.error = None

    def __repr__(self):
        ms = f"{self.duration * 1e3:.2f}ms" if self.duration is not None \
            else "…"
        return f"Span({self.name} {ms})"


class TraceCollector:
    """Interceptor that records spans with caller/callee nesting."""

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self.spans: list[Span] = []
        self._stack: list[Span] = []

    def __call__(self, name, method, args, kwargs):
        parent = self._stack[-1].span_id if self._stack else None
        span = Span(next(_span_ids), parent, name, self.clock())
        self.spans.append(span)
        self._stack.append(span)
        try:
            return method(*args, **kwargs)
        except Exception as exc:
            span.error = repr(exc)
            raise
        finally:
            span.duration = self.clock() - span.start
            self._stack.pop()


def print_tracer(name, method, args, kwargs):
    """The reference's proxy_trace equivalent: enter/exit prints."""
    print(f"TRACE enter {name}{args!r}")
    try:
        return method(*args, **kwargs)
    finally:
        print(f"TRACE exit  {name}")


def trace_all_methods(instance, interceptor, only=None) -> list[str]:
    """Wrap every public bound method of `instance` with
    interceptor(name, method, args, kwargs).  Returns the wrapped names.
    `only` restricts to the given method names."""
    import inspect

    wrapped = []
    for name in dir(instance):
        if name.startswith("_"):
            continue
        if only is not None and name not in only:
            continue
        # inspect statically first: plain getattr would EXECUTE property
        # getters (service classes here define several, with side effects)
        static = inspect.getattr_static(instance, name, None)
        if not (inspect.isfunction(static) or inspect.ismethod(static)):
            continue
        method = getattr(instance, name)
        if not callable(method) or not hasattr(method, "__self__"):
            continue

        @functools.wraps(method)
        def wrapper(*args, _name=name, _method=method, **kwargs):
            return interceptor(_name, _method, args, kwargs)

        wrapper.__traced__ = method
        instance.__dict__[name] = wrapper
        wrapped.append(name)
    return wrapped


def untrace(instance) -> None:
    """Remove all trace wrappers installed by trace_all_methods."""
    for name, value in list(instance.__dict__.items()):
        if hasattr(value, "__traced__"):
            del instance.__dict__[name]
