# Small LRU cache used by the recorder and audio framing elements.
# (capability parity: aiko_services/utilities/lru_cache.py:22-47)

from __future__ import annotations

from collections import OrderedDict

__all__ = ["LRUCache"]


class LRUCache:
    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("LRUCache size must be positive")
        self.size = size
        self._data: OrderedDict = OrderedDict()

    def get(self, key, default=None):
        if key in self._data:
            self._data.move_to_end(key)
            return self._data[key]
        return default

    def put(self, key, value):
        if key in self._data:
            self._data.move_to_end(key)
        self._data[key] = value
        while len(self._data) > self.size:
            self._data.popitem(last=False)

    def delete(self, key):
        self._data.pop(key, None)

    def keys(self):
        return list(self._data.keys())

    def values(self):
        return list(self._data.values())

    def items(self):
        return list(self._data.items())

    def __contains__(self, key):
        return key in self._data

    def __len__(self):
        return len(self._data)
