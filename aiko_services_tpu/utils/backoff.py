# Jittered exponential backoff, shared by every retry site in the tree
# (pipeline remote-hop retry, ProcessManager/LifeCycleManager restart
# policies, MQTT reconnect).  One formula, one place: base doubles per
# attempt, capped, then stretched by up to `jitter` fraction so a fleet
# of retriers fans out instead of stampeding in lockstep.

from __future__ import annotations

import random

__all__ = ["jittered_backoff"]


def jittered_backoff(base: float, attempt: int, cap: float,
                     jitter: float, rng: random.Random) -> float:
    """Delay before the attempt-th retry (attempt >= 1)."""
    delay = min(base * (2 ** (attempt - 1)), cap)
    return delay * (1.0 + jitter * rng.random())
