# Logging: console and transport-backed (distributed) handlers.
#
# Capability parity with the reference logger
# (reference: aiko_services/utilities/logger.py:92-164): per-subsystem level
# env vars, a handler that publishes records to a pub/sub topic, and ring
# buffering of records until the transport is connected.

from __future__ import annotations

import logging
import os
import threading
from collections import deque

__all__ = ["get_logger", "get_log_level_name", "TransportLoggingHandler"]

_FORMAT = "%(asctime)s %(levelname)-5s %(name)s: %(message)s"
_DATE_FORMAT = "%H:%M:%S"
_RING_SIZE = 128


def get_log_level_name(logger_or_level) -> str:
    level = getattr(logger_or_level, "level", logger_or_level)
    return logging.getLevelName(level)


def _resolve_level(name: str) -> int:
    env = os.environ.get(f"AIKO_TPU_LOG_LEVEL_{name.upper()}",
                         os.environ.get("AIKO_TPU_LOG_LEVEL",
                                        os.environ.get("AIKO_LOG_LEVEL")))
    if not env:
        return logging.INFO
    try:
        return int(env)
    except ValueError:
        return logging.getLevelName(env.upper()) \
            if isinstance(logging.getLevelName(env.upper()), int) \
            else logging.INFO


def get_logger(name: str, level=None, handler=None) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = handler or logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FORMAT, _DATE_FORMAT))
        logger.addHandler(h)
        logger.propagate = False
    logger.setLevel(level if level is not None else _resolve_level(name))
    return logger


class TransportLoggingHandler(logging.Handler):
    """Publishes log records to `topic` on a Message transport.

    `message` may be the transport itself or a zero-arg callable
    returning it (lazy: actors are often built before the runtime's
    transport connects).  Records emitted before the transport is up are
    ring-buffered (up to 128) and flushed on first successful publish.
    """

    def __init__(self, message, topic: str):
        super().__init__()
        self.message = message
        self.topic = topic
        self._ring: deque = deque(maxlen=_RING_SIZE)
        # re-entrancy guard (per thread): transport.publish may itself
        # log (broker diagnostics, slow-consumer warnings) and that
        # record would arrive right back here — drop it instead of
        # recursing until the stack dies
        self._emitting = threading.local()
        self.dropped_reentrant = 0

    def _transport(self):
        return self.message() if callable(self.message) else self.message

    def emit(self, record):
        if getattr(self._emitting, "active", False):
            self.dropped_reentrant += 1
            return
        self._emitting.active = True
        try:
            try:
                payload = self.format(record)
            except Exception:
                return
            transport = self._transport()
            if transport is not None and transport.connected():
                while self._ring:
                    transport.publish(self.topic, self._ring.popleft())
                transport.publish(self.topic, payload)
            else:
                self._ring.append(payload)
        finally:
            self._emitting.active = False
