# Diagnostic named lock + runtime lock-order race detector.
#
# Base behavior (capability parity: aiko_services/utilities/lock.py:20-29,
# hardened): records the holder's location string AND thread, warns on
# contention, and raises RuntimeError on misuse — double release, release
# without acquire, release by a thread that is not the holder (all three
# silently corrupted the holder record before).
#
# Opt-in lock-order checking (AIKO_LOCK_CHECK=1, wired into the test
# suite by tests/conftest.py): every nested acquisition records an edge
# lock_held -> lock_acquired in a process-global order graph, keyed by
# lock NAME.  A new edge that closes a cycle is a potential ABBA
# deadlock — reported with BOTH acquisition stacks (where each direction
# was first taken) via lock_check_report(), and logged.  Like kernel
# lockdep, the detector is conservative: it flags inconsistent ordering
# even when observed from a single thread, because the same two code
# paths on two threads WILL deadlock.  Re-entrant acquire of the same
# lock instance (guaranteed self-deadlock for this non-reentrant lock)
# raises immediately instead of hanging.
#
# Overhead when disabled: one module-global boolean test per
# acquire/release.

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from dataclasses import dataclass

__all__ = [
    "Lock", "LockOrderViolation", "enable_lock_check",
    "lock_check_enabled", "lock_check_report", "lock_check_reset",
]

_logger = logging.getLogger("aiko_tpu.lock")


def _env_enabled() -> bool:
    return os.environ.get("AIKO_LOCK_CHECK", "").lower() \
        not in ("", "0", "false", "no", "off")


_enabled = _env_enabled()


def lock_check_enabled() -> bool:
    return _enabled


def enable_lock_check(on: bool = True) -> None:
    """Turn the lock-order detector on/off at runtime (the env var
    AIKO_LOCK_CHECK sets the initial state at import)."""
    global _enabled
    _enabled = bool(on)


@dataclass(frozen=True)
class LockOrderViolation:
    """A potential deadlock: both acquisition orders were observed."""
    cycle: tuple            # lock names, e.g. ("B", "A", "B")
    this_stack: str         # where the cycle-closing order was taken
    prior_stack: str        # where the opposite order was first taken

    def __str__(self):
        chain = " -> ".join(self.cycle)
        return (f"potential deadlock: lock order cycle {chain}\n"
                f"--- this acquisition ---\n{self.this_stack}"
                f"--- prior (opposite) acquisition ---\n"
                f"{self.prior_stack}")


class _OrderChecker:
    """Process-global acquisition-order graph over diagnostic locks."""

    def __init__(self):
        # guards the checker's own graph; deliberately a raw lock — the
        # checker cannot instrument itself
        self._lock = threading.Lock()   # graft: disable=lint-raw-lock
        self._edges: dict[tuple, str] = {}      # (a, b) -> first stack
        self._succ: dict[str, set] = {}
        self._violations: list[LockOrderViolation] = []
        self._local = threading.local()

    # -- per-thread held stack --------------------------------------------
    def held(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- graph -------------------------------------------------------------
    def _path(self, src: str, dst: str):
        """DFS path src -> dst through recorded edges, or None."""
        visited = {src}
        trail = [(src, [src])]
        while trail:
            name, path = trail.pop()
            if name == dst:
                return path
            for successor in self._succ.get(name, ()):
                if successor not in visited:
                    visited.add(successor)
                    trail.append((successor, path + [successor]))
        return None

    def before_acquire(self, lock: "Lock") -> None:
        for held_id, _name in self.held():
            if held_id == id(lock):
                raise RuntimeError(
                    f"Lock {lock.name}: re-entrant acquire by thread "
                    f"{threading.current_thread().name!r} would "
                    f"self-deadlock (held since {lock._holder})")

    def after_acquire(self, lock: "Lock") -> None:
        held = self.held()
        if held:
            stack_text = None       # built only when a NEW edge appears:
            with self._lock:        # steady state stays a dict lookup
                for _held_id, held_name in held:
                    if held_name == lock.name:
                        continue
                    edge = (held_name, lock.name)
                    if edge in self._edges:
                        continue
                    if stack_text is None:
                        stack_text = "".join(
                            traceback.format_stack(limit=16)[:-2])
                    # does the REVERSE order already exist?  check before
                    # inserting so the cycle path excludes this edge
                    path = self._path(lock.name, held_name)
                    self._edges[edge] = stack_text
                    self._succ.setdefault(held_name, set()).add(lock.name)
                    if path:
                        prior = self._edges.get(tuple(path[:2]), "")
                        violation = LockOrderViolation(
                            cycle=tuple(path + [lock.name]),
                            this_stack=stack_text, prior_stack=prior)
                        self._violations.append(violation)
                        _logger.error("%s", violation)
        held.append((id(lock), lock.name))

    def after_release(self, lock: "Lock") -> None:
        held = self.held()
        for index in range(len(held) - 1, -1, -1):
            if held[index][0] == id(lock):
                del held[index]
                return

    # -- reporting ---------------------------------------------------------
    def report(self) -> list:
        with self._lock:
            return list(self._violations)

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._succ.clear()
            self._violations.clear()


_checker = _OrderChecker()


def lock_check_report() -> list:
    """All LockOrderViolations observed since the last reset."""
    return _checker.report()


def lock_check_reset() -> None:
    _checker.reset()


class Lock:
    """Named lock with holder diagnostics and misuse errors.

    acquire(location) records WHERE and on WHICH THREAD the lock was
    taken; contention logs a warning naming both.  release() raises
    RuntimeError on double release, release without acquire, and release
    by a non-holder thread.  With AIKO_LOCK_CHECK=1 every acquisition
    also feeds the global lock-order cycle detector above."""

    def __init__(self, name: str, logger=None):
        self.name = name
        self._logger = logger
        # the wrapped primitive itself (this IS the diagnostic wrapper)
        self._lock = threading.Lock()   # graft: disable=lint-raw-lock
        self._holder: str | None = None
        self._holder_thread: threading.Thread | None = None
        self._acquired_at = 0.0
        self.max_hold = 0.0             # longest observed hold (seconds)

    def acquire(self, location: str):
        if _enabled:
            _checker.before_acquire(self)
        if self._holder is not None and self._logger:
            holder_thread = self._holder_thread
            self._logger.warning(
                "Lock %s: %s waiting on holder %s [thread %s]",
                self.name, location, self._holder,
                holder_thread.name if holder_thread else "?")
        self._lock.acquire()
        self._holder = location
        self._holder_thread = threading.current_thread()
        self._acquired_at = time.monotonic()
        if _enabled:
            _checker.after_acquire(self)

    def release(self):
        holder, holder_thread = self._holder, self._holder_thread
        if holder is None or holder_thread is None:
            raise RuntimeError(
                f"Lock {self.name}: release without acquire "
                f"(double release, or never acquired) by thread "
                f"{threading.current_thread().name!r}")
        current = threading.current_thread()
        if holder_thread is not current:
            raise RuntimeError(
                f"Lock {self.name}: released by thread {current.name!r} "
                f"but held by {holder_thread.name!r} "
                f"(acquired at {holder})")
        held_for = time.monotonic() - self._acquired_at
        if held_for > self.max_hold:
            self.max_hold = held_for
        self._holder = None
        self._holder_thread = None
        if _enabled:
            _checker.after_release(self)
        self._lock.release()

    def in_use(self) -> bool:
        return self._holder is not None

    def holder(self):
        """(location, thread name) of the current holder, or None."""
        holder, holder_thread = self._holder, self._holder_thread
        if holder is None:
            return None
        return holder, holder_thread.name if holder_thread else "?"

    def __enter__(self):
        self.acquire("context-manager")
        return self

    def __exit__(self, *exc):
        self.release()
        return False
