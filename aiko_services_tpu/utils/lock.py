# Diagnostic named lock: records holder location, warns on contention.
# (capability parity: aiko_services/utilities/lock.py:20-29)

from __future__ import annotations

import threading

__all__ = ["Lock"]


class Lock:
    def __init__(self, name: str, logger=None):
        self.name = name
        self._logger = logger
        self._lock = threading.Lock()
        self._holder: str | None = None

    def acquire(self, location: str):
        if self._holder is not None and self._logger:
            self._logger.warning(
                "Lock %s: %s waiting on holder %s",
                self.name, location, self._holder)
        self._lock.acquire()
        self._holder = location

    def release(self):
        self._holder = None
        self._lock.release()

    def in_use(self) -> bool:
        return self._holder is not None

    def __enter__(self):
        self.acquire("context-manager")
        return self

    def __exit__(self, *exc):
        self.release()
        return False
