# Environment-variable configuration system.
#
# Capability parity with the reference configuration module
# (reference: aiko_services/utilities/configuration.py:73-162): namespace,
# hostname/pid/username identity, message-transport selection and host/port
# resolution.  Env vars use the AIKO_TPU_ prefix; the reference's AIKO_ names
# are honoured as fallbacks so operators can migrate without re-tooling.

from __future__ import annotations

import os
import socket
import getpass
import dataclasses

__all__ = [
    "get_namespace", "get_hostname", "get_pid", "get_username",
    "TransportConfig", "get_transport_configuration",
]

_DEFAULT_NAMESPACE = "aiko"
_DEFAULT_MQTT_PORT = 1883


def _env(name: str, default=None):
    return os.environ.get(f"AIKO_TPU_{name}", os.environ.get(
        f"AIKO_{name}", default))


def get_namespace() -> str:
    return _env("NAMESPACE", _DEFAULT_NAMESPACE)


def get_hostname() -> str:
    return socket.gethostname().split(".")[0]


def get_pid() -> str:
    return str(os.getpid())


def get_username() -> str:
    try:
        return getpass.getuser()
    except Exception:
        return _env("USERNAME", "unknown")


@dataclasses.dataclass
class TransportConfig:
    transport: str = "memory"        # "memory" | "mqtt"
    host: str = "localhost"
    port: int = _DEFAULT_MQTT_PORT
    username: str | None = None
    password: str | None = None
    tls: bool = False


def get_transport_configuration() -> TransportConfig:
    """Resolve the control-plane transport from the environment.

    Default is the in-memory broker (single-host, test-friendly).  Setting
    AIKO_TPU_MQTT_HOST selects the MQTT transport, mirroring how the
    reference bootstraps from AIKO_MQTT_HOST.
    """
    host = _env("MQTT_HOST")
    transport = _env("MESSAGE_TRANSPORT", "mqtt" if host else "memory")
    return TransportConfig(
        transport=transport,
        host=host or "localhost",
        port=int(_env("MQTT_PORT", _DEFAULT_MQTT_PORT)),
        username=_env("USERNAME_MQTT", _env("USERNAME")),
        password=_env("PASSWORD"),
        tls=str(_env("MQTT_TLS", "")).lower() in ("1", "true", "yes"),
    )
