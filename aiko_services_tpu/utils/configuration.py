# Environment-variable configuration system.
#
# Capability parity with the reference configuration module
# (reference: aiko_services/utilities/configuration.py:73-162): namespace,
# hostname/pid/username identity, message-transport selection and host/port
# resolution.  Env vars use the AIKO_TPU_ prefix; the reference's AIKO_ names
# are honoured as fallbacks so operators can migrate without re-tooling.

from __future__ import annotations

import os
import socket
import getpass
import dataclasses

__all__ = [
    "get_namespace", "get_hostname", "get_pid", "get_username",
    "TransportConfig", "get_transport_configuration",
    "BootstrapResponder", "discover_bootstrap", "BOOTSTRAP_PORT",
]

_DEFAULT_NAMESPACE = "aiko"
_DEFAULT_MQTT_PORT = 1883
BOOTSTRAP_PORT = 4149       # reference: utilities/configuration.py:136-162


def _env(name: str, default=None):
    return os.environ.get(f"AIKO_TPU_{name}", os.environ.get(
        f"AIKO_{name}", default))


def get_namespace() -> str:
    return _env("NAMESPACE", _DEFAULT_NAMESPACE)


def get_hostname() -> str:
    return socket.gethostname().split(".")[0]


def get_pid() -> str:
    return str(os.getpid())


def pid_start_time(pid: int):
    """Kernel start time of `pid` (jiffies since boot from
    /proc/<pid>/stat field 22), or None when unknowable.  A (pid,
    start_time) pair uniquely names a process for the machine's
    uptime — the identity check that a bare pid (recyclable) or a
    cmdline substring (spoofable, brittle) cannot give.  Off-Linux
    falls back to `ps -o lstart=` (a wall-clock string; still unique
    per incarnation)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # comm (field 2) may contain spaces/parens — split after the
        # LAST ')' so field indices are stable
        fields = stat[stat.rindex(")") + 2:].split()
        return int(fields[19])          # starttime is field 22 overall
    except (OSError, ValueError, IndexError):
        import subprocess
        try:
            out = subprocess.run(
                ["ps", "-p", str(pid), "-o", "lstart="],
                capture_output=True, text=True, timeout=2).stdout.strip()
            return out or None
        except (OSError, subprocess.SubprocessError):
            return None


def pid_verified(pid: int, marker: str = "aiko",
                 start_time=None) -> bool:
    """True when `pid` is alive AND still names the process we think
    it does — guards SIGKILL paths against pid reuse by an unrelated
    process (a stale dashboard row or pid file can outlive its
    process).

    When `start_time` (a value previously captured via
    `pid_start_time`) is given, identity is exact: the live process's
    start time must match.  Otherwise falls back to the weaker
    cmdline-contains-`marker` heuristic.  When neither source can
    answer, the result is False (callers degrade to a graceful
    stop)."""
    if start_time is not None:
        return pid_start_time(pid) == start_time
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().replace(b"\0", b" ").decode(
                "utf-8", "replace")
    except OSError:
        import subprocess
        try:
            cmdline = subprocess.run(
                ["ps", "-p", str(pid), "-o", "command="],
                capture_output=True, text=True, timeout=2).stdout
        except (OSError, subprocess.SubprocessError):
            return False
    return marker in cmdline


def get_username() -> str:
    try:
        return getpass.getuser()
    except Exception:
        return _env("USERNAME", "unknown")


@dataclasses.dataclass
class TransportConfig:
    transport: str = "memory"        # "memory" | "mqtt"
    host: str = "localhost"
    port: int = _DEFAULT_MQTT_PORT
    username: str | None = None
    password: str | None = None
    tls: bool = False


def get_transport_configuration() -> TransportConfig:
    """Resolve the control-plane transport from the environment.

    Default is the in-memory broker (single-host, test-friendly).  Setting
    AIKO_TPU_MQTT_HOST selects the MQTT transport, mirroring how the
    reference bootstraps from AIKO_MQTT_HOST.
    """
    host = _env("MQTT_HOST")
    transport = _env("MESSAGE_TRANSPORT", "mqtt" if host else "memory")
    return TransportConfig(
        transport=transport,
        host=host or "localhost",
        port=int(_env("MQTT_PORT", _DEFAULT_MQTT_PORT)),
        username=_env("USERNAME_MQTT", _env("USERNAME")),
        password=_env("PASSWORD"),
        tls=str(_env("MQTT_TLS", "")).lower() in ("1", "true", "yes"),
    )


# -- UDP broadcast bootstrap (DNS-less device discovery) ---------------------
# Protocol parity with the reference (utilities/configuration.py:136-162):
# a device broadcasts "boot?" on BOOTSTRAP_PORT; any host running a
# responder answers "boot <host> <port>" with its transport endpoint.

class BootstrapResponder:
    """Answers "boot?" broadcasts with this host's transport endpoint.
    Runs a small daemon thread (network I/O, not event-loop work)."""

    def __init__(self, host: str | None = None, port: int | None = None,
                 bind: str = "", bootstrap_port: int = BOOTSTRAP_PORT):
        config = get_transport_configuration()
        self.host = host or config.host
        self.port = port or config.port
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind, bootstrap_port))
        self._sock.settimeout(0.5)
        self._running = True
        import threading
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while self._running:
            try:
                data, address = self._sock.recvfrom(64)
            except socket.timeout:
                continue
            except OSError:
                return
            if data.strip() == b"boot?":
                reply = f"boot {self.host} {self.port}".encode()
                try:
                    self._sock.sendto(reply, address)
                except OSError:
                    pass

    def stop(self) -> None:
        self._running = False
        self._sock.close()


def discover_bootstrap(timeout: float = 2.0,
                       bootstrap_port: int = BOOTSTRAP_PORT):
    """Broadcast "boot?" and return (host, port) of the first responder,
    or None — lets DNS-less devices find the control-plane broker."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_BROADCAST, 1)
    sock.settimeout(timeout)
    try:
        sock.sendto(b"boot?", ("255.255.255.255", bootstrap_port))
    except OSError:
        # broadcast unavailable (containers): try loopback
        try:
            sock.sendto(b"boot?", ("127.0.0.1", bootstrap_port))
        except OSError:
            sock.close()
            return None
    try:
        while True:
            data, _address = sock.recvfrom(128)
            parts = data.decode(errors="replace").split()
            if len(parts) != 3 or parts[0] != "boot":
                continue            # stray datagram: keep listening
            try:
                return parts[1], int(parts[2])
            except ValueError:
                continue            # malformed port: keep listening
    except socket.timeout:
        return None
    finally:
        sock.close()
