# Ordered DAG with the pipeline-graph DSL.
#
# Capability parity with the reference Graph/Node
# (reference: aiko_services/utilities/graph.py:45-150): named nodes with
# ordered successors, deterministic traversal order, and a classmethod that
# parses the s-expression graph DSL  "(a (b d) (c d))"  including per-edge
# property dicts  "(a (b (x: y)))"  used for pipeline fan-in/out name mapping.
#
# Fresh design: explicit topological ordering (Kahn, stable by insertion
# order) rather than DFS emission, plus predecessor maps — the pipeline
# engine needs both to validate dataflow and to schedule stages.

from __future__ import annotations

from .sexpr import parse_sexpr, ParseError

__all__ = ["Graph", "Node", "GraphError"]


class GraphError(ValueError):
    pass


class Node:
    __slots__ = ("name", "element", "properties", "successors")

    def __init__(self, name: str, element=None, properties=None):
        self.name = name
        self.element = element           # payload (e.g. a PipelineElement)
        self.properties = properties or {}   # per-edge properties by head name
        self.successors: list[str] = []

    def add_successor(self, name: str):
        if name not in self.successors:
            self.successors.append(name)

    def __repr__(self):
        return f"Node({self.name} -> {self.successors})"


class Graph:
    """Insertion-ordered DAG of named nodes."""

    def __init__(self, head_names=()):
        self._nodes: dict[str, Node] = {}
        self._head_names = list(head_names)

    # -- construction -----------------------------------------------------
    def add(self, name: str, element=None, properties=None) -> Node:
        if name in self._nodes:
            raise GraphError(f"duplicate node: {name}")
        node = Node(name, element, properties)
        self._nodes[name] = node
        return node

    def add_edge(self, tail: str, head: str):
        # validate the head NOW: a dangling successor used to slip in
        # silently and only surface later in predecessor_map()
        if head not in self._nodes:
            raise GraphError(
                f"add_edge {tail}->{head}: unknown head node {head!r} "
                f"(add it first)")
        self.node(tail).add_successor(head)

    def remove(self, name: str):
        self._nodes.pop(name, None)
        for node in self._nodes.values():
            if name in node.successors:
                node.successors.remove(name)

    # -- access -----------------------------------------------------------
    def node(self, name: str) -> Node:
        try:
            return self._nodes[name]
        except KeyError:
            raise GraphError(f"unknown node: {name}") from None

    def __contains__(self, name):
        return name in self._nodes

    def __len__(self):
        return len(self._nodes)

    def nodes(self):
        return list(self._nodes.values())

    def node_names(self):
        return list(self._nodes)

    @property
    def head_names(self):
        return list(self._head_names)

    def successors(self, name: str):
        return list(self.node(name).successors)

    def predecessors(self, name: str) -> list[str]:
        return [n.name for n in self._nodes.values() if name in n.successors]

    def predecessor_map(self) -> dict[str, list[str]]:
        preds = {name: [] for name in self._nodes}
        for node in self._nodes.values():
            for succ in node.successors:
                if succ not in preds:
                    raise GraphError(
                        f"edge {node.name}->{succ} to undeclared node")
                preds[succ].append(node.name)
        return preds

    # -- ordering ---------------------------------------------------------
    def topological_order(self) -> list[Node]:
        """Stable Kahn topological sort; raises GraphError on cycles."""
        preds = self.predecessor_map()
        indegree = {name: len(p) for name, p in preds.items()}
        ready = [n for n in self._nodes if indegree[n] == 0]
        order = []
        while ready:
            name = ready.pop(0)
            order.append(self._nodes[name])
            for succ in self._nodes[name].successors:
                indegree[succ] -= 1
                if indegree[succ] == 0:
                    ready.append(succ)
        if len(order) != len(self._nodes):
            cyclic = [n for n, d in indegree.items() if d > 0]
            raise GraphError(f"cycle detected involving: {cyclic}")
        return order

    def __iter__(self):
        return iter(self.topological_order())

    def __repr__(self):
        return f"Graph({[n.name for n in self.topological_order()]})"

    # -- DSL --------------------------------------------------------------
    @classmethod
    def traverse(cls, dsl, node_properties_callback=None) -> "Graph":
        """Build a Graph from the s-expression DSL.

        "(a (b d) (c d))" : a→b, a→c, b→d, c→d (diamond).
        "(a (b (x: y)))"  : a→b with edge properties {"x": "y"} recorded on
        node a, keyed by successor name ("b"), and reported via
        node_properties_callback(tail_name, head_name, properties).
        Accepts a single DSL string or a list of strings (multiple heads).
        """
        graph = cls()
        if isinstance(dsl, str):
            dsl = [dsl]
        for expr_text in dsl:
            expr = parse_sexpr(expr_text)
            if isinstance(expr, str):
                expr = [expr]
            if not isinstance(expr, list) or not expr:
                raise GraphError(f"bad graph expression: {expr_text!r}")
            head = cls._traverse_expr(graph, expr, node_properties_callback)
            graph._head_names.append(head)
        return graph

    @staticmethod
    def _ensure(graph: "Graph", name: str) -> Node:
        return graph._nodes[name] if name in graph else graph.add(name)

    @classmethod
    def _traverse_expr(cls, graph, expr, props_cb) -> str:
        """expr = [tail, successor...]; successor = atom | [sub-expr] and an
        optional trailing dict of edge properties.  Returns the tail name."""
        tail_name = expr[0]
        if not isinstance(tail_name, str):
            raise GraphError(f"node name must be an atom, got {tail_name!r}")
        tail = cls._ensure(graph, tail_name)
        for successor in expr[1:]:
            if isinstance(successor, str):
                cls._ensure(graph, successor)
                tail.add_successor(successor)
            elif isinstance(successor, dict):
                raise GraphError(
                    f"edge properties must follow a successor name: "
                    f"{successor!r}")
            elif isinstance(successor, list) and successor:
                # "(b (x: y) d)" — properties dict directly after head name
                head_name = successor[0]
                rest = successor[1:]
                if rest and isinstance(rest[0], dict):
                    properties = rest.pop(0)
                    cls._ensure(graph, head_name)
                    tail.properties[head_name] = properties
                    if props_cb:
                        props_cb(tail_name, head_name, properties)
                sub_head = cls._traverse_expr(
                    graph, [head_name] + rest, props_cb)
                tail.add_successor(sub_head)
            else:
                raise GraphError(f"bad successor: {successor!r}")
        return tail_name
