# Dynamic module loading for pipeline element deployment.
# (capability parity: aiko_services/utilities/importer.py:24-38 — load by
# dotted module name or filesystem path, with a cache)

from __future__ import annotations

import importlib
import importlib.util
import os
import sys

__all__ = ["load_module", "load_class"]

_cache: dict[str, object] = {}


def load_module(name_or_path: str):
    """Load a module by dotted name ("pkg.mod") or file path ("/x/mod.py")."""
    if name_or_path in _cache:
        return _cache[name_or_path]
    if name_or_path.endswith(".py") or os.path.sep in name_or_path:
        path = os.path.abspath(name_or_path)
        mod_name = os.path.splitext(os.path.basename(path))[0]
        spec = importlib.util.spec_from_file_location(mod_name, path)
        if spec is None or spec.loader is None:
            raise ImportError(f"cannot load module from {path}")
        module = importlib.util.module_from_spec(spec)
        sys.modules.setdefault(mod_name, module)
        spec.loader.exec_module(module)
    else:
        module = importlib.import_module(name_or_path)
    _cache[name_or_path] = module
    return module


def load_class(module_name: str, class_name: str):
    module = load_module(module_name)
    try:
        return getattr(module, class_name)
    except AttributeError:
        raise ImportError(
            f"module {module_name!r} has no class {class_name!r}") from None
