# S-expression wire format: the canonical control-plane payload encoding.
#
# Capability parity with the reference parser/generator
# (reference: aiko_services/utilities/parser.py:74-202): lists, nested lists,
# "key: value" association lists, length-prefixed binary-safe tokens "N:raw",
# and the (command param...) RPC framing used by every service protocol.
#
# This is a fresh implementation: a single-pass tokenizer + recursive-descent
# reader, with symmetric generate() that round-trips every parse() result.

from __future__ import annotations

__all__ = [
    "ParseError", "parse", "parse_sexpr", "generate", "generate_sexpr",
    "parse_int", "parse_float", "parse_number", "list_to_dict", "dict_to_list",
]


class ParseError(ValueError):
    """Raised when a payload is not a well-formed S-expression."""


_WHITESPACE = " \t\r\n"
_DELIMITERS = "()" + _WHITESPACE


def _tokenize(text: str):
    """Yield tokens: '(', ')', or atom strings.

    Atoms may be length-prefixed for binary safety: "7:a b (c)" is the single
    7-character atom "a b (c)".  A trailing ':' marks a dict key ("key:"),
    which is preserved on the token so the reader can build association lists.
    """
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch in _WHITESPACE:
            i += 1
        elif ch in "()":
            yield ch
            i += 1
        else:
            j = i
            while j < n and text[j] not in _DELIMITERS:
                # length-prefixed atom: digits then ':' then exactly L chars
                if text[j] == ":" and j > i and text[i:j].isdigit():
                    length = int(text[i:j])
                    start = j + 1
                    if start + length > n:
                        raise ParseError(
                            f"length-prefixed token overruns payload at {i}")
                    yield _Raw(text[start:start + length])
                    i = start + length
                    break
                j += 1
            else:
                yield text[i:j]
                i = j
                continue
            # inner break (length-prefixed token) already advanced i
            if i > j:
                continue


class _Raw(str):
    """An atom produced from a length-prefixed token (never a dict key)."""


_native_parse = None


def parse_sexpr(payload: str):
    """Parse a payload into nested Python lists/dicts of strings.

    A parenthesised group whose members all look like "key:" value pairs is
    returned as a dict (insertion-ordered); otherwise a list.  Top level must
    be a single expression; bare atoms are returned as-is.

    Dispatches to the C extension (native/aiko_native.cpp) when built;
    this function is the reference implementation and the fallback."""
    global _native_parse
    if _native_parse is None:
        try:
            from ..native import NATIVE_AVAILABLE, native_parse_sexpr
            _native_parse = native_parse_sexpr if NATIVE_AVAILABLE \
                else False
        except Exception:
            _native_parse = False
    if _native_parse:
        try:
            return _native_parse(payload)
        except RuntimeError:
            pass        # non-ascii payload: fall through to Python
    return _parse_sexpr_py(payload)


def _parse_sexpr_py(payload: str):
    tokens = list(_tokenize(payload))
    if not tokens:
        return []
    expr, rest = _read(tokens, 0)
    if rest != len(tokens):
        raise ParseError(f"trailing tokens after expression: {tokens[rest:]}")
    return expr


def _read(tokens, pos):
    token = tokens[pos]
    if token == "(":
        items = []
        pos += 1
        while pos < len(tokens) and tokens[pos] != ")":
            item, pos = _read(tokens, pos)
            items.append(item)
        if pos >= len(tokens):
            raise ParseError("unbalanced '(' in payload")
        return _maybe_dict(items), pos + 1
    if token == ")":
        raise ParseError("unbalanced ')' in payload")
    return token, pos + 1


def _maybe_dict(items):
    """(a: 1 b: (c d)) → {"a": "1", "b": ["c", "d"]}; else keep the list."""
    if not items or len(items) % 2:
        return items
    keys = items[0::2]
    if all(isinstance(k, str) and not isinstance(k, _Raw)
           and k.endswith(":") and len(k) > 1 for k in keys):
        return {k[:-1]: v for k, v in zip(keys, items[1::2])}
    return items


def parse(payload: str):
    """Parse an RPC payload "(command param...)" → (command, [params]).

    Bare "command" (no parens) is accepted.  Returns ("", []) for empty input.
    """
    expr = parse_sexpr(payload)
    if isinstance(expr, str):
        return expr, []
    if isinstance(expr, dict):
        return "", [expr]
    if not expr:
        return "", []
    command = expr[0]
    if not isinstance(command, str):
        raise ParseError(f"command must be an atom, got {command!r}")
    return command, expr[1:]


def _needs_quoting(atom: str) -> bool:
    if atom == "":
        return True
    return any(c in _DELIMITERS for c in atom) or \
        atom.endswith(":") or \
        (":" in atom and atom.split(":", 1)[0].isdigit())


def _safe_dict_key(key) -> bool:
    return isinstance(key, str) and key != "" and ":" not in key and \
        not any(c in _DELIMITERS for c in key)


def generate_sexpr(obj) -> str:
    """Inverse of parse_sexpr for str / list / tuple / dict / scalars.

    Dicts whose keys contain delimiters or ':' cannot be expressed in the
    "key: value" association form; they are emitted as a flat alternating
    list (data preserved, dict-ness not)."""
    if isinstance(obj, dict):
        if all(_safe_dict_key(k) for k in obj):
            inner = " ".join(
                f"{k}: {generate_sexpr(v)}" for k, v in obj.items())
            return f"({inner})"
        return generate_sexpr(dict_to_list(obj))
    if isinstance(obj, (list, tuple)):
        return "(" + " ".join(generate_sexpr(i) for i in obj) + ")"
    if isinstance(obj, bool):
        return "true" if obj else "false"
    if obj is None:
        return "()"
    atom = str(obj)
    if _needs_quoting(atom):
        return f"{len(atom)}:{atom}"
    return atom


def generate(command: str, parameters=()) -> str:
    """Generate an RPC payload: generate("aloha", ["Pele"]) → "(aloha Pele)"."""
    parts = [command] + [generate_sexpr(p) for p in parameters]
    return "(" + " ".join(parts) + ")"


def parse_int(value, default=0) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def parse_float(value, default=0.0) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        return default


def parse_bool(value, default=False) -> bool:
    """Coerce a wire-delivered parameter to bool.

    S-expression parameters arrive as strings, so bare truthiness is a
    trap: "false"/"0" are truthy Python strings.  Reference parameters
    have the same string-over-MQTT shape
    (reference share.py ECProducer payloads)."""
    if isinstance(value, bool):
        return value
    if isinstance(value, (int, float)):
        return bool(value)
    if isinstance(value, str):
        lowered = value.strip().lower()
        if lowered in ("true", "t", "yes", "on", "1"):
            return True
        if lowered in ("false", "f", "no", "off", "0", ""):
            return False
        return default
    if value is None:
        return default
    return bool(value)


def parse_number(value, default=0):
    """int if possible, else float, else default."""
    try:
        return int(value)
    except (TypeError, ValueError):
        try:
            return float(value)
        except (TypeError, ValueError):
            return default


def list_to_dict(items) -> dict:
    """Flat ["a", "1", "b", "2"] → {"a": "1", "b": "2"}."""
    if len(items) % 2:
        raise ParseError(f"odd item count for dict: {items}")
    return dict(zip(items[0::2], items[1::2]))


def dict_to_list(mapping: dict) -> list:
    out = []
    for k, v in mapping.items():
        out.extend((k, v))
    return out
