from .sexpr import (                                        # noqa: F401
    ParseError, parse, parse_sexpr, generate, generate_sexpr,
    parse_int, parse_float, parse_number, parse_bool,
    list_to_dict, dict_to_list,
)
from .backoff import jittered_backoff                       # noqa: F401
from .graph import Graph, Node, GraphError                  # noqa: F401
from .configuration import (                                # noqa: F401
    get_namespace, get_hostname, get_pid, get_username, pid_verified,
    TransportConfig, get_transport_configuration,
)
from .logger import (                                       # noqa: F401
    get_logger, get_log_level_name, TransportLoggingHandler,
)
from .lru_cache import LRUCache                             # noqa: F401
from .importer import load_module, load_class               # noqa: F401
from .lock import (                                         # noqa: F401
    Lock, LockOrderViolation, enable_lock_check, lock_check_enabled,
    lock_check_report, lock_check_reset,
)
