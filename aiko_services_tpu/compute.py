# ComputeRuntime: the TPU execution backend service.
#
# This is the north-star component (BASELINE.json): the piece that hosts
# compiled jax programs behind the control plane.  The reference has no
# equivalent — its elements call CUDA models inline on the event loop
# (reference: examples/speech/speech_elements.py:217-250), serializing
# every tensor through MQTT.  Here:
#   * a ComputeRuntime owns the device mesh and a table of compiled
#     functions ("programs"), placed with logical-axis shardings;
#   * pipeline elements submit work through a BatchingScheduler — frames
#     from many streams coalesce into MXU-sized batches with a bounded
#     wait (<150 ms p50 target);
#   * it is a Service: its mesh geometry, program table, and batch stats
#     are EC-shared, so dashboards and lifecycle managers see device
#     health (SURVEY.md §7 "two-plane consistency").

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable

from .ops.batching import BatchingScheduler, ShapeBuckets
from .service import ServiceProtocol
from .actor import Actor
from .utils import get_logger

__all__ = ["ComputeRuntime", "CompiledProgram", "PROTOCOL_COMPUTE",
           "resolve_pipelined"]


def resolve_pipelined(pipelined, mode: str) -> bool:
    """Pipelined results complete on a LATER event-loop turn; a sync
    caller blocking on scheduler.drain(force=True) would hang forever.
    Every element that exposes both knobs must route them through here."""
    return bool(pipelined) and mode != "sync"

PROTOCOL_COMPUTE = ServiceProtocol("compute")


@dataclass
class CompiledProgram:
    name: str
    fn: Callable                  # jitted: fn(batch_payload) -> results
    buckets: ShapeBuckets | None
    scheduler: BatchingScheduler | None
    first_call_times: dict       # bucket -> first-call wall seconds
                                 # (compile + first execution, honestly
                                 # named: the two are not separable here)
    in_flight: dict | None = None   # pipelined: {"now": N, "peak": N}
    recent_service: Any = None   # deque[(bucket, seconds)] of recent
                                 # batch service times (post-compile)


class ComputeRuntime(Actor):
    """Owns the mesh; hosts compiled programs; schedules batches.

    mesh=None → single-device.  Programs are registered with a collate
    function (list of payloads → batch arrays) and a split function
    (batch results → per-item results); the runtime wires them to a
    BatchingScheduler driven off the EventEngine.
    """

    def __init__(self, runtime, name: str = "compute", mesh=None,
                 drive_period: float = 0.005):
        share = {"device_count": 0, "program_count": 0}
        super().__init__(runtime, name, PROTOCOL_COMPUTE, share=share)
        self.logger = get_logger(f"compute.{name}")
        self._mesh = mesh
        self.drive_period = drive_period
        self.programs: dict[str, CompiledProgram] = {}
        self._timers: list[int] = []
        # pipelined results: worker thread syncs device results (GIL
        # released during transfer) and deliveries cross back onto the
        # event loop through this queue
        self._results_queue = f"compute.results.{name}"
        self._worker = None
        self._worker_queue = None
        runtime.event.add_queue_handler(self._deliver_results,
                                        self._results_queue)
        import jax
        self._devices = list(mesh.devices.flat) if mesh is not None \
            else jax.devices()[:1]
        self.ec_producer.update("device_count", len(self._devices))
        self.ec_producer.update(
            "mesh", dict(mesh.shape) if mesh is not None else {})
        self.ec_producer.update("platform", self._devices[0].platform)
        self.ec_producer.update("device_kind", self._devices[0].device_kind)
        self.refresh_device_health()
        # keep device health LIVE: dashboards must see HBM pressure
        # building, not a boot-time snapshot
        self._timers.append(runtime.event.add_timer_handler(
            self.refresh_device_health, period=10.0))

    def refresh_device_health(self) -> None:
        """Publish per-device memory occupancy into the EC share so
        lifecycle managers / dashboards watch device health live
        (SURVEY.md §7 two-plane consistency).  TPU backends report
        bytes_in_use/bytes_limit; backends without memory_stats (CPU)
        just report presence."""
        for device in self._devices:
            stats = {}
            try:
                stats = device.memory_stats() or {}
            except Exception:
                pass
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            value = round(100.0 * in_use / limit, 1) \
                if in_use is not None and limit else -1
            key = f"device.{device.id}.mem_pct"
            # dedup: EC updates fan out to every leaseholder — no-op
            # republishes every 10 s would spam each consumer forever
            if self.ec_producer.get(key) != value:
                self.ec_producer.update(key, value)

    @property
    def mesh(self):
        if self._mesh is None:
            from .parallel import single_device_mesh
            self._mesh = single_device_mesh()
        return self._mesh

    # -- direct (unbatched) programs ---------------------------------------
    def register_program(self, name: str, fn, donate_argnums=()) -> None:
        """Register a jittable fn for direct invocation via run()."""
        import jax
        compiled = jax.jit(fn, donate_argnums=donate_argnums)
        self.programs[name] = CompiledProgram(name, compiled, None, None,
                                              {})
        self.ec_producer.update("program_count", len(self.programs))

    def run(self, name: str, *args):
        program = self.programs[name]
        start = time.perf_counter()
        result = program.fn(*args)
        program.first_call_times.setdefault("direct",
                                         time.perf_counter() - start)
        return result

    # -- batched programs ---------------------------------------------------
    def register_batched(self, name: str, fn, buckets,
                         collate, split, max_batch: int = 32,
                         max_wait: float = 0.05,
                         pipelined: bool = False,
                         max_in_flight: int = 4) -> BatchingScheduler:
        """Register a batched program.

        fn(bucket, batch_arrays) -> batch_results (jit-compiled per
        bucket by the caller or internally static);
        collate(bucket, payloads) -> batch_arrays;
        split(batch_results, count) -> list of per-item results.

        pipelined=True moves split() — where the blocking device sync
        lives — onto a worker thread and delivers callbacks through the
        event queue: batch N+1's collate/upload overlaps batch N's device
        compute.  Callbacks then fire on a later event-loop turn, so
        callers must drive the engine (drain(force=True) alone does not
        complete items).  max_in_flight bounds how many dispatched
        batches may be awaiting their device sync at once (≥2 for any
        overlap; deeper keeps uploads of rounds k+1..k+d covering round
        k's compute+sync on thin links at the cost of per-batch latency
        and device queue memory).  Returns the scheduler."""
        program_holder = {}
        in_flight = {"now": 0, "peak": 0}

        def process_batch(bucket, items):
            payloads = [item.payload for item in items]
            batch = collate(bucket, payloads)
            start = time.perf_counter()
            results = fn(bucket, batch)       # async dispatch under jit
            if pipelined:
                in_flight["now"] += 1
                in_flight["peak"] = max(in_flight["peak"],
                                        in_flight["now"])
                self._worker_submit(program_holder["program"], bucket,
                                    items, results, split, start)
                return None                   # ownership transferred
            program = program_holder["program"]
            per_item = split(results, len(items))    # device sync
            elapsed = time.perf_counter() - start
            if bucket not in program.first_call_times:
                # first call = compile + run; do NOT feed it to the
                # service estimator or deadline admission would fire
                # spuriously for the whole warm period
                program.first_call_times[bucket] = elapsed
                self.ec_producer.update(
                    f"first_call.{name}.{bucket}", round(elapsed, 3))
            else:
                scheduler.observe_service_time(bucket, elapsed)
                program.recent_service.append((bucket, elapsed))
            self._publish_stats(name, scheduler)
            return per_item

        if not isinstance(buckets, ShapeBuckets):
            buckets = ShapeBuckets(buckets)
        gate = (lambda: in_flight["now"] < int(max_in_flight)) \
            if pipelined else None
        scheduler = BatchingScheduler(process_batch, buckets,
                                      max_batch=max_batch,
                                      max_wait=max_wait,
                                      clock=self.runtime.event.clock.now,
                                      dispatch_gate=gate,
                                      metrics_labels={"program": name})
        from collections import deque
        program = CompiledProgram(name, fn, buckets, scheduler, {})
        program.in_flight = in_flight
        program.recent_service = deque(maxlen=512)
        program_holder["program"] = program
        self.programs[name] = program
        self._timers.append(scheduler.attach(self.runtime.event,
                                             self.drive_period))
        self.ec_producer.update("program_count", len(self.programs))
        return scheduler

    def submit(self, name: str, stream_id: str, payload, length: int,
               callback, deadline: float | None = None) -> None:
        program = self.programs[name]
        if program.scheduler is None:
            raise ValueError(f"program {name} is not batched")
        program.scheduler.submit(stream_id, payload, length, callback,
                                 deadline=deadline)

    # -- pipelined results path ---------------------------------------------
    def _worker_submit(self, program, bucket, items, results, split,
                       start) -> None:
        import queue as _queue
        import threading
        if self._worker is None:
            self._worker_queue = _queue.Queue()
            self._worker = threading.Thread(
                target=self._worker_loop, name=f"compute.{self.name}",
                daemon=True)
            self._worker.start()
        self._worker_queue.put((program, bucket, items, results, split,
                                start))

    def _worker_loop(self) -> None:
        while True:
            job = self._worker_queue.get()
            if job is None:
                return
            program, bucket, items, results, split, start = job
            try:
                per_item = split(results, len(items))   # blocks on device
                if len(per_item) != len(items):
                    raise RuntimeError(
                        f"split returned {len(per_item)} results for "
                        f"{len(items)} items")
            except Exception as exc:
                per_item = [exc] * len(items)
            elapsed = time.perf_counter() - start
            self.runtime.event.queue_put(
                self._results_queue,
                (program, bucket, items, per_item, elapsed))

    def _deliver_results(self, _queue_name, job, _put_time) -> None:
        program, bucket, items, per_item, elapsed = job
        if program.in_flight is not None:
            program.in_flight["now"] = max(
                0, program.in_flight["now"] - 1)
        if bucket not in program.first_call_times:
            # keyed by the program's fixed bucket ladder — bounded:
            # graft: disable=lint-unbounded-cache
            program.first_call_times[bucket] = elapsed
            self.ec_producer.update(f"first_call.{program.name}.{bucket}",
                                    round(elapsed, 3))
        elif program.scheduler is not None:
            program.scheduler.observe_service_time(bucket, elapsed)
            if program.recent_service is not None:
                # audited: deque(maxlen=512)  # graft: disable=lint-unbounded-queue
                program.recent_service.append((bucket, elapsed))
        if program.scheduler is not None:
            self._publish_stats(program.name, program.scheduler)
        for item, result in zip(items, per_item):
            item.callback(item.stream_id, result)

    def _publish_stats(self, name: str, scheduler) -> None:
        self.ec_producer.update(f"batch.{name}.batches",
                                scheduler.stats["batches"])
        mean_size = round(scheduler.mean_batch_size(), 2)
        mean_wait_ms = round(scheduler.mean_wait() * 1000.0, 2)
        self.ec_producer.update(f"batch.{name}.mean_size", mean_size)
        self.ec_producer.update(f"batch.{name}.mean_wait_ms",
                                mean_wait_ms)
        # rolling levels beside the mirrored cumulative counters: the
        # dashboard metrics pane and a Prometheus scrape both see them
        from .observe.metrics import default_registry
        registry = default_registry()
        labels = {"program": name}
        registry.gauge("batch_mean_size",
                       "mean dispatched batch size", labels).set(mean_size)
        registry.gauge("batch_mean_wait_ms",
                       "mean batch-former queue wait",
                       labels).set(mean_wait_ms)

    # -- placement ----------------------------------------------------------
    def place_params(self, params, param_axes, rules=None):
        """Shard a parameter tree onto this runtime's mesh."""
        from .parallel import shard_pytree
        return shard_pytree(params, param_axes, self.mesh, rules)

    def stop(self) -> None:
        for timer in self._timers:
            self.runtime.event.remove_timer_handler(timer)
        for program in self.programs.values():
            if program.scheduler is not None:
                program.scheduler.drain(force=True)
        if self._worker is not None:
            self._worker_queue.put(None)
            self._worker.join(timeout=10.0)
            self._worker = None
        self.runtime.event.remove_queue_handler(self._results_queue)
        super().stop()
