# ComputeRuntime: the TPU execution backend service.
#
# This is the north-star component (BASELINE.json): the piece that hosts
# compiled jax programs behind the control plane.  The reference has no
# equivalent — its elements call CUDA models inline on the event loop
# (reference: examples/speech/speech_elements.py:217-250), serializing
# every tensor through MQTT.  Here:
#   * a ComputeRuntime owns the device mesh and a table of compiled
#     functions ("programs"), placed with logical-axis shardings;
#   * pipeline elements submit work through a BatchingScheduler — frames
#     from many streams coalesce into MXU-sized batches with a bounded
#     wait (<150 ms p50 target);
#   * it is a Service: its mesh geometry, program table, and batch stats
#     are EC-shared, so dashboards and lifecycle managers see device
#     health (SURVEY.md §7 "two-plane consistency").

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable

from .ops.batching import BatchingScheduler, ShapeBuckets
from .service import ServiceProtocol
from .actor import Actor
from .utils import get_logger

__all__ = ["ComputeRuntime", "CompiledProgram", "PROTOCOL_COMPUTE"]

PROTOCOL_COMPUTE = ServiceProtocol("compute")


@dataclass
class CompiledProgram:
    name: str
    fn: Callable                  # jitted: fn(batch_payload) -> results
    buckets: ShapeBuckets | None
    scheduler: BatchingScheduler | None
    compile_times: dict          # bucket -> seconds


class ComputeRuntime(Actor):
    """Owns the mesh; hosts compiled programs; schedules batches.

    mesh=None → single-device.  Programs are registered with a collate
    function (list of payloads → batch arrays) and a split function
    (batch results → per-item results); the runtime wires them to a
    BatchingScheduler driven off the EventEngine.
    """

    def __init__(self, runtime, name: str = "compute", mesh=None,
                 drive_period: float = 0.005):
        share = {"device_count": 0, "program_count": 0}
        super().__init__(runtime, name, PROTOCOL_COMPUTE, share=share)
        self.logger = get_logger(f"compute.{name}")
        self._mesh = mesh
        self.drive_period = drive_period
        self.programs: dict[str, CompiledProgram] = {}
        self._timers: list[int] = []
        import jax
        self._devices = list(mesh.devices.flat) if mesh is not None \
            else jax.devices()[:1]
        self.ec_producer.update("device_count", len(self._devices))
        self.ec_producer.update(
            "mesh", dict(mesh.shape) if mesh is not None else {})
        self.ec_producer.update("platform", self._devices[0].platform)

    @property
    def mesh(self):
        if self._mesh is None:
            from .parallel import single_device_mesh
            self._mesh = single_device_mesh()
        return self._mesh

    # -- direct (unbatched) programs ---------------------------------------
    def register_program(self, name: str, fn, donate_argnums=()) -> None:
        """Register a jittable fn for direct invocation via run()."""
        import jax
        compiled = jax.jit(fn, donate_argnums=donate_argnums)
        self.programs[name] = CompiledProgram(name, compiled, None, None,
                                              {})
        self.ec_producer.update("program_count", len(self.programs))

    def run(self, name: str, *args):
        program = self.programs[name]
        start = time.perf_counter()
        result = program.fn(*args)
        program.compile_times.setdefault("direct",
                                         time.perf_counter() - start)
        return result

    # -- batched programs ---------------------------------------------------
    def register_batched(self, name: str, fn, buckets,
                         collate, split, max_batch: int = 32,
                         max_wait: float = 0.05) -> BatchingScheduler:
        """Register a batched program.

        fn(bucket, batch_arrays) -> batch_results (jit-compiled per
        bucket by the caller or internally static);
        collate(bucket, payloads) -> batch_arrays;
        split(batch_results, count) -> list of per-item results.
        Returns the scheduler (elements submit through it)."""
        program_holder = {}

        def process_batch(bucket, items):
            payloads = [item.payload for item in items]
            batch = collate(bucket, payloads)
            start = time.perf_counter()
            results = fn(bucket, batch)
            program = program_holder["program"]
            if bucket not in program.compile_times:
                program.compile_times[bucket] = \
                    time.perf_counter() - start
                self.ec_producer.update(
                    f"compile.{name}.{bucket}",
                    round(program.compile_times[bucket], 3))
            self._publish_stats(name, scheduler)
            return split(results, len(items))

        if not isinstance(buckets, ShapeBuckets):
            buckets = ShapeBuckets(buckets)
        scheduler = BatchingScheduler(process_batch, buckets,
                                      max_batch=max_batch,
                                      max_wait=max_wait,
                                      clock=self.runtime.event.clock.now)
        program = CompiledProgram(name, fn, buckets, scheduler, {})
        program_holder["program"] = program
        self.programs[name] = program
        self._timers.append(scheduler.attach(self.runtime.event,
                                             self.drive_period))
        self.ec_producer.update("program_count", len(self.programs))
        return scheduler

    def submit(self, name: str, stream_id: str, payload, length: int,
               callback) -> None:
        program = self.programs[name]
        if program.scheduler is None:
            raise ValueError(f"program {name} is not batched")
        program.scheduler.submit(stream_id, payload, length, callback)

    def _publish_stats(self, name: str, scheduler) -> None:
        self.ec_producer.update(f"batch.{name}.batches",
                                scheduler.stats["batches"])
        self.ec_producer.update(f"batch.{name}.mean_size",
                                round(scheduler.mean_batch_size(), 2))
        self.ec_producer.update(f"batch.{name}.mean_wait_ms",
                                round(scheduler.mean_wait() * 1000.0, 2))

    # -- placement ----------------------------------------------------------
    def place_params(self, params, param_axes, rules=None):
        """Shard a parameter tree onto this runtime's mesh."""
        from .parallel import shard_pytree
        return shard_pytree(params, param_axes, self.mesh, rules)

    def stop(self) -> None:
        for timer in self._timers:
            self.runtime.event.remove_timer_handler(timer)
        for program in self.programs.values():
            if program.scheduler is not None:
                program.scheduler.drain(force=True)
        super().stop()
