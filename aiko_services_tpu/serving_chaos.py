# Serving-plane fault injection (ISSUE 19, robustness tentpole).
#
# The chaos layer (transport/chaos.py) injects WIRE faults — drops,
# duplication, partitions, crashes.  This module injects the serving
# plane's own failure modes, the ones a TPU fleet actually sees:
#
#   * preemption — the scheduler reclaims the device at a round
#     boundary (GKE spot / Borg preemption lands as a SIGTERM with a
#     grace window): the armed round never runs, the watchdog fires an
#     alert, and the decoder drains — in-flight slots checkpoint into
#     the prefix cache so the evacuated requests resume elsewhere with
#     their progress intact;
#   * pool-growth refusal — HBM exhaustion: the paged BlockPool's free
#     list runs dry and growth is refused for a window, modelling a
#     device that cannot take another retrace/allocation.  The refusal
#     surfaces as a caught fault, an alert, and a drain — never a
#     wedged pump;
#   * hung scan — a compiled step stops returning in budget (driver
#     stall, thermal throttle): the watchdog compares each pump round's
#     wall time against a threshold and escalates the same way.
#
# Every fault ends in the SAME escalation — on_alert callbacks then
# ContinuousDecoder.drain — because that is the production invariant
# worth testing: no fault class loses a request; they all route through
# checkpoint-evacuate-migrate (chaos_soak --migrate drives this end to
# end).  Deterministic by construction: faults arm at explicit round
# numbers and the clock is injectable.

from __future__ import annotations

import time

from .observe.metrics import MirroredStats, default_registry
from .utils import get_logger

__all__ = ["ChaosPoolRefusal", "ChaosDecoder"]


class ChaosPoolRefusal(RuntimeError):
    """Injected HBM-exhaustion fault: the pool refused to grow."""


class ChaosDecoder:
    """Fault-injection wrapper around a ContinuousDecoder's pump.

    Register `chaos.pump` with the engine wherever `decoder.pump`
    would go (flatout handler / timer).  Unarmed, it is a transparent
    pass-through — the decoder's behavior is bit-identical.  Armed
    faults fire at deterministic round numbers, count themselves,
    invoke every `on_alert(kind, detail)` callback, and arm the
    decoder's graceful drain with the configured deadline."""

    def __init__(self, decoder, name: str = "chaos", clock=None,
                 drain_deadline: float = 0.0, registry=None):
        self.decoder = decoder
        self.name = str(name)
        self.logger = get_logger(f"serving.chaos.{name}")
        # injectable wall clock (tests substitute a fake so the hung-
        # scan threshold is deterministic); the ENGINE clock is wrong
        # here — a hung scan hangs wall time, not virtual time
        self._clock = clock or time.perf_counter
        # deadline handed to decoder.drain on escalation: 0.0 means
        # "checkpoint at the next round boundary" (preemption grace
        # windows are short; anything in flight checkpoints NOW)
        self.drain_deadline = float(drain_deadline)
        self.on_alert: list = []          # callbacks (kind, detail)
        # evacuation route for the drained requests: descriptors land
        # here, and in on_evacuate's hands when set (a migrator, a
        # re-router) — otherwise each request's own callback delivers
        # the partial generation (degraded, never silently dropped)
        self.on_evacuate = None
        self.evacuated: list = []
        self.round = 0
        self._preempt_round: int | None = None
        self._refuse_until_round: int | None = None
        self._hung_threshold: float | None = None
        self._wrapped_alloc = None
        self.stats = MirroredStats(
            {"rounds": 0, "preemptions": 0, "alloc_refusals": 0,
             "hung_scans": 0, "alerts": 0, "drains": 0},
            metric="chaos_decoder_events_total",
            help="injected serving-plane faults by kind",
            registry=registry or default_registry(),
            labels={"chaos": self.name})

    # -- arming ------------------------------------------------------------
    def arm_preemption(self, at_round: int) -> None:
        """Preempt the device at pump round `at_round` (1-based): the
        round does not run, the alert fires, the decoder drains."""
        self._preempt_round = int(at_round)

    def arm_alloc_refusal(self, rounds: int) -> None:
        """Refuse pool GROWTH for the next `rounds` pump rounds: an
        alloc the free list can satisfy proceeds, one that would grow
        the device arrays raises ChaosPoolRefusal — caught by pump(),
        alerted, escalated to drain.  Paged decoders only."""
        pool = getattr(self.decoder, "pool", None)
        if pool is None:
            raise ValueError("alloc refusal needs a paged decoder "
                             "(dense caches have no block pool)")
        self._refuse_until_round = self.round + max(1, int(rounds))
        if self._wrapped_alloc is None:
            self._wrapped_alloc = pool.alloc_blocks

            def refusing_alloc(count, tenant=""):
                count = int(count)
                if self._refusing() and count > len(pool._free):
                    self.stats["alloc_refusals"] += 1
                    raise ChaosPoolRefusal(
                        f"chaos {self.name}: pool growth refused "
                        f"({count} blocks wanted, "
                        f"{len(pool._free)} free)")
                return self._wrapped_alloc(count, tenant=tenant)

            pool.alloc_blocks = refusing_alloc

    def arm_hung_scan(self, threshold_s: float) -> None:
        """Escalate when one pump round's wall time exceeds
        `threshold_s` — the compiled step stopped returning in
        budget."""
        self._hung_threshold = float(threshold_s)

    def disarm(self) -> None:
        """Drop every armed fault and restore the wrapped pool."""
        self._preempt_round = None
        self._hung_threshold = None
        self._refuse_until_round = None
        self._restore_alloc()

    def _refusing(self) -> bool:
        return self._refuse_until_round is not None and \
            self.round <= self._refuse_until_round

    def _restore_alloc(self) -> None:
        if self._wrapped_alloc is not None:
            self.decoder.pool.alloc_blocks = self._wrapped_alloc
            self._wrapped_alloc = None

    # -- escalation --------------------------------------------------------
    def _alert(self, kind: str, detail: dict) -> None:
        self.stats["alerts"] += 1
        self.logger.warning("chaos %s: %s fault at round %d: %r",
                            self.name, kind, self.round, detail)
        for callback in list(self.on_alert):
            try:
                callback(kind, detail)
            except Exception:
                self.logger.exception(
                    "chaos %s: on_alert callback raised", self.name)

    def _evacuated(self, descriptor: dict) -> None:
        self.evacuated.append(descriptor)
        route = self.on_evacuate
        if route is not None:
            try:
                route(descriptor)
            except Exception:
                self.logger.exception(
                    "chaos %s: on_evacuate route failed for %s",
                    self.name, descriptor["request_id"])
            return
        try:
            descriptor["callback"](descriptor["request_id"],
                                   descriptor["generated"])
        except Exception:
            self.logger.exception(
                "chaos %s: degraded delivery failed for %s",
                self.name, descriptor["request_id"])

    def _escalate(self, kind: str, detail: dict) -> None:
        self._alert(kind, detail)
        if not self.decoder.draining:
            self.stats["drains"] += 1
        # queued (never-admitted) requests come back as the drain's
        # return value — route them like the checkpointed ones; a
        # dropped descriptor would be a lost request
        for descriptor in self.decoder.drain(
                deadline=self.drain_deadline,
                on_evacuate=self._evacuated):
            self._evacuated(descriptor)

    # -- the wrapped pump --------------------------------------------------
    def pump(self) -> None:
        self.round += 1
        self.stats["rounds"] += 1
        if self._preempt_round is not None and \
                self.round >= self._preempt_round:
            # the device is gone for this round; the grace window is
            # exactly long enough to checkpoint at the next boundary
            self._preempt_round = None
            self.stats["preemptions"] += 1
            self._escalate("preemption", {"round": self.round})
            return
        started = self._clock()
        try:
            self.decoder.pump()
        except ChaosPoolRefusal as exc:
            self._escalate("pool_refusal",
                           {"round": self.round, "error": str(exc)})
            return
        finally:
            if self._refuse_until_round is not None and \
                    self.round >= self._refuse_until_round:
                self._refuse_until_round = None
                self._restore_alloc()
        elapsed = self._clock() - started
        if self._hung_threshold is not None and \
                elapsed > self._hung_threshold:
            self.stats["hung_scans"] += 1
            self._escalate("hung_scan", {"round": self.round,
                                         "elapsed_s": elapsed})
