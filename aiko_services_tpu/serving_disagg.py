# Disaggregated prefill/decode serving plane (ISSUE 14, ROADMAP item 2).
#
# BENCH_r05 measured prefill riding the decode host gap (~9.2 ms/step of
# deferred-admit prefill per round before PR 7) and MULTICHIP_r0x shows
# multi-chip capacity idle for serving.  Production LLM serving converged
# on the fix (DistServe, Splitwise): split prefill and decode into
# separately-scaled pools so prompt bursts never dilate inter-token
# latency.  Every building block already exists in this repo — this
# module is the composition:
#
#   * PrefillRuntime — a role-tagged actor owning a ContinuousDecoder +
#     PrefixKVCache pair whose ONLY job is computing prompt KV: each
#     request prefills (max_new_tokens=1), the existing retire-harvest
#     drops the prompt blocks into its cache, and the chain ships to the
#     decode side as a KV-transfer envelope (transport/wire.py
#     encode_kv_transfer) over the peer data plane — int8 {"q","s"}
#     blocks cross bit-exact, so disaggregated greedy output is
#     BIT-IDENTICAL to colocated by construction;
#   * PrefillClient — the decode-side KV admit path: routes prompts to a
#     prefill runtime by remaining deadline (ops/admission.DeadlineRouter
#     — short-budget prompts to the least-loaded runtime), installs the
#     shipped chain into the decode decoder's PrefixKVCache
#     (install_chain), and submits the request — the prefix-admit scatter
#     copies the chain into the slot with NO forward pass, so the decode
#     pool's scan only ever stalls on the tiny ragged suffix.  Chains the
#     decode side already holds ship as HANDLES — the hash chain is
#     content-addressed, so only a start index crosses, never the bytes
#     (ROADMAP item 3 residue b);
#   * local-prefill fallback ladder — no pool, transfer timeout after a
#     retry, corrupt payload, or layout mismatch all degrade to the
#     decode runtime prefilling locally, counted, never a dropped
#     request (the PR 6 peer→broker ladder, one level up);
#   * two_pool_autoscalers — the PR 9 autoscaler instantiated per role:
#     the prefill pool scales on its queue depth / TTFT backlog, the
#     decode pool on fleet-merged ITL p95 / batch wait, each through its
#     own LifeCycleManager.scale_to;
#   * DisaggHarness — the CPU-runnable two-pool plane behind the
#     lat_llama_disagg_* bench rung, scripts/disagg_smoke.py, and the
#     chaos tests (registrar + peer-enabled prefill/decode runtimes over
#     one MemoryBroker and one engine).
#
# The reference has no serving at all (its LLM hop is a blocking HTTP
# call); DistServe (OSDI'24) and Splitwise (ISCA'24) are the design
# ancestors for the split itself.

from __future__ import annotations

import time
import uuid

import numpy as np

from .actor import Actor
from .observe import tracing
from .observe.metrics import MirroredStats, default_registry
from .ops.admission import DeadlineRouter
from .service import ServiceFilter, ServiceProtocol, ServiceTags
from .transport import wire
from .utils import get_logger

__all__ = ["PROTOCOL_PREFILL", "ROLE_PREFILL", "ROLE_DECODE",
           "ROLE_COLOCATED", "role_tag", "tag_role", "PrefillRuntime",
           "PrefillClient", "SessionMigrator", "two_pool_autoscalers",
           "DisaggHarness"]

PROTOCOL_PREFILL = ServiceProtocol("prefill")
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_COLOCATED = "colocated"


def role_tag(role: str) -> str:
    """The discovery-record tag form of a serving role."""
    return f"role={role}"


def tag_role(service, role: str) -> None:
    """Tag a service's registrar record with its serving role and
    re-register so the changed record propagates (the registrar
    suppresses identical re-adds but forwards changed ones)."""
    service.add_tags([role_tag(role)])
    runtime = service.runtime
    if runtime.registrar is not None and runtime.message is not None:
        runtime._register_service(service)


class PrefillRuntime(Actor):
    """A prefill-pool member: computes prompt KV and ships it.

    RPC (binary envelope on {topic_path}/in):
        (prefill transfer_id reply_topic tenant have_tokens
         {"tokens": i32[*]})
    The reply is a KV-transfer envelope on `reply_topic`, carrying
    chain blocks [have_tokens/block, ...) — blocks the caller declared
    it already holds are handles (indices), not bytes.

    The decoder is an ordinary ContinuousDecoder with a bound
    PrefixKVCache: a request prefills, emits one token, retires, and
    the retire-harvest inserts its prompt blocks — repeated prefixes
    across requests (shared system prompts) prefill once here too.
    Geometry MUST match the decode pool's (same config / kv dtype /
    block size); the transfer declares the donor layout and the decode
    side refuses a mismatch."""

    def __init__(self, runtime, name: str = "prefill", *,
                 params=None, config=None, decoder=None, cache=None,
                 block_tokens: int = 32, cache_mb: int = 256,
                 max_slots: int = 8, prefill_buckets=(128,),
                 steps_per_sync: int = 1,
                 prefill_chunk: int | None = None,
                 decoder_opts: dict | None = None,
                 pump_period: float = 0.002,
                 batch_window: float = 0.0,
                 chunk_stream: bool | None = None, registry=None):
        super().__init__(runtime, name, PROTOCOL_PREFILL,
                         tags=[role_tag(ROLE_PREFILL)])
        from .serving import ContinuousDecoder, PrefixKVCache
        self.logger = get_logger(f"disagg.prefill.{name}")
        self._registry = registry or default_registry()
        if decoder is not None:
            self.cache = cache if cache is not None \
                else decoder.prefix_cache
            self.decoder = decoder
        else:
            self.cache = cache or PrefixKVCache(
                block_tokens=int(block_tokens),
                max_bytes=int(cache_mb) << 20,
                name=f"{name}.cache", registry=self._registry)
            self.decoder = ContinuousDecoder(
                params, config, max_slots=int(max_slots),
                prefill_buckets=tuple(prefill_buckets),
                steps_per_sync=int(steps_per_sync),
                # chunked prefill forced on (largest bucket) like
                # PE_LlamaAgent's prefix path: without it the decoder
                # TRUNCATES prompts to the largest bucket, the harvest
                # keys on the truncated tail, and _ship's full-prompt
                # match finds nothing — every long transfer would ship
                # zero blocks (review finding)
                prefill_chunk=int(prefill_chunk)
                if prefill_chunk else max(prefill_buckets),
                name=name, prefix_cache=self.cache,
                registry=self._registry, **(decoder_opts or {}))
        if self.cache is None:
            raise ValueError(
                "PrefillRuntime needs a decoder with a bound "
                "PrefixKVCache (the harvest IS the product)")
        # chunk streaming (ISSUE 17): when the donor prefills in
        # chunks, ship each chunk's finished blocks the moment the
        # chunk lands instead of holding the whole prompt's KV for one
        # ship-on-finish envelope — the transfer overlaps the rest of
        # the prefill compute.  Default: on whenever chunked prefill
        # is on (there is nothing to stream otherwise).
        if chunk_stream is None:
            chunk_stream = bool(self.decoder.prefill_chunk)
        self.chunk_stream = bool(chunk_stream)
        # pump_period <= 0 drives the pump flat-out (once per engine
        # step) instead of on a periodic timer — what the single-engine
        # harness uses so a busy pump cannot starve the engine's
        # message queues (see DisaggHarness)
        self._flatout = pump_period is not None and pump_period <= 0
        if self._flatout:
            runtime.event.add_flatout_handler(self.decoder.pump)
        else:
            self.decoder.attach(runtime.event, period=pump_period)
        self.stats = MirroredStats(
            {"requests": 0, "computed": 0, "blocks_shipped": 0,
             "bytes_shipped": 0, "handle_blocks": 0, "refused": 0,
             "empty_ships": 0, "envelopes": 0, "batched_envelopes": 0,
             "chunks_shipped": 0, "chunk_blocks": 0},
            metric="prefill_runtime_events_total",
            help="prefill-runtime events by kind",
            registry=self._registry, skip=("bytes_shipped",),
            labels={"runtime": name})
        # prefill-side transfer batching (ISSUE 15 satellite, PR 14
        # residue b): finished transfers to the SAME destination within
        # `batch_window` seconds coalesce into one kv_transfer_batch
        # envelope — a prompt burst's per-envelope wire cost amortizes.
        # 0 disables (ship-on-finish, the PR 14 behavior).
        self.batch_window = max(0.0, float(batch_window))
        self._ship_queue: dict[str, list] = {}
        self._ship_timers: dict[str, int] = {}
        self._batched_counter = self._registry.counter(
            "disagg_transfer_batched_total",
            "KV transfers that rode a coalesced batch envelope",
            labels={"runtime": name})
        # the prefill pool's OWN scale signal (ISSUE 14): prompts
        # waiting for KV compute — what the prefill-pool autoscaler
        # reads as TTFT backlog
        self._queue_gauge = self._registry.gauge(
            "prefill_queue_depth",
            "prompts queued or resident in the prefill runtime",
            labels={"runtime": name})

    def _publish_depth(self) -> None:
        self._queue_gauge.set(len(self.decoder._pending) +
                              self.decoder.active_count)

    # -- RPC ---------------------------------------------------------------
    def prefill(self, transfer_id, reply_topic, tenant, have_tokens,
                box) -> None:
        """Compute prompt KV for `tokens` and ship the chain blocks the
        caller does not already hold."""
        self.stats["requests"] += 1
        try:
            tokens = [int(t) for t in np.asarray(box["tokens"])]
            have = max(0, int(str(have_tokens)))
        except (TypeError, KeyError, ValueError) as exc:
            self.stats["refused"] += 1
            self.logger.warning("prefill %s: malformed request %r: %r",
                                self.name, transfer_id, exc)
            return
        # truncate EXACTLY like decoder.submit will, so the harvest,
        # the match below, and the shipped tokens all key one prompt
        tokens = tokens[-_prompt_cap(self.decoder):] or [0]
        tenant = str(tenant)
        context = tracing.current_trace()
        # chunk-stream cursor: the next chain block index to ship.
        # Shared by the per-chunk progress callback and the final ship
        # so a block crosses the wire exactly once.
        state = {"cursor": None}

        def computed(_rid, generated):
            self._publish_depth()
            with tracing.activate(context):
                self._ship(str(transfer_id), str(reply_topic), tenant,
                           have, tokens,
                           int(generated[0]) if generated else None,
                           cursor=state["cursor"])

        progress = None
        if self.chunk_stream:
            def progress(request, finished):
                if finished:
                    return   # the final ship (with first_token) owns the tail
                with tracing.activate(context):
                    self._ship_chunk(str(transfer_id), str(reply_topic),
                                     tenant, have, tokens, request, state)

        accepted = self.decoder.submit(str(transfer_id), tokens, 1,
                                       computed, tenant=tenant,
                                       progress_callback=progress)
        if not accepted:
            self.stats["refused"] += 1
        self._publish_depth()

    def _ship(self, transfer_id: str, reply_topic: str, tenant: str,
              have: int, tokens, first_token, cursor=None) -> None:
        self.stats["computed"] += 1
        cache = self.cache
        block = cache.block_tokens
        keys, hit = cache.match(tenant, tokens)
        if hit == 0 and len(tokens) >= block:
            # the computed prompt produced no cached chain (budget
            # refused every insert?): ship nothing but say so — a
            # silent empty transfer looks exactly like success
            self.stats["empty_ships"] += 1
            self.logger.warning(
                "prefill %s: transfer %s computed %d tokens but the "
                "cache holds none of its chain; shipping empty",
                self.name, transfer_id, len(tokens))
        start_block = min(have // block, hit // block)
        # handle-shipping accounting keys on the caller's holdings, not
        # on what chunk streaming already moved — count before the
        # cursor advances the window
        self.stats["handle_blocks"] += start_block
        if cursor:
            # chunk streaming already shipped blocks below the cursor;
            # the final envelope carries only the tail (plus
            # first_token, which must always cross)
            start_block = min(max(start_block, int(cursor)),
                              hit // block)
        blocks = self._wire_blocks(keys[start_block:hit // block])
        context = tracing.current_trace()
        payload = wire.encode_kv_transfer(
            transfer_id, tenant, tokens, start_block, block,
            cache.wire_layout(), blocks, first_token=first_token,
            trace=context.to_fields(self.runtime.event.clock.now())
            if context is not None else None)
        self.stats["blocks_shipped"] += len(blocks)
        self.stats["bytes_shipped"] += len(payload)
        self._post(reply_topic, payload)

    def _ship_chunk(self, transfer_id: str, reply_topic: str,
                    tenant: str, have: int, tokens, request,
                    state: dict) -> None:
        """Ship the chain blocks a finished prefill chunk just made
        durable (ISSUE 17 chunk streaming).  Runs from the decoder's
        progress callback — the request is still resident, so the rows
        are harvested into the cache first and shipped from there with
        the same block path the final ship uses."""
        cache = self.cache
        block = cache.block_tokens
        self.decoder.harvest_progress(request)
        pos = int(request.prefill_pos)
        keys, hit = cache.match(tenant, list(tokens[:pos]))
        if state["cursor"] is None:
            # blocks the caller already holds never ship, streamed or
            # not — the cursor starts at the handle boundary
            state["cursor"] = min(have // block, hit // block)
        cursor = state["cursor"]
        end = hit // block
        if end <= cursor:
            return
        blocks = self._wire_blocks(keys[cursor:end])
        context = tracing.current_trace()
        payload = wire.encode_kv_transfer(
            transfer_id, tenant, list(tokens[:end * block]), cursor,
            block, cache.wire_layout(), blocks, final=False,
            trace=context.to_fields(self.runtime.event.clock.now())
            if context is not None else None)
        state["cursor"] = end
        self.stats["chunks_shipped"] += 1
        self.stats["chunk_blocks"] += len(blocks)
        self.stats["blocks_shipped"] += len(blocks)
        self.stats["bytes_shipped"] += len(payload)
        self._post(reply_topic, payload)

    def _wire_blocks(self, keys) -> list:
        return _chain_wire_blocks(self.cache, keys)

    def _post(self, reply_topic: str, payload: bytes) -> None:
        """Ship one finished transfer: immediately, or coalesced with
        other same-destination transfers inside the batch window
        (ISSUE 15 satellite).  Either way the envelope rides the peer
        channel when the caller's reply topic is pinned, the broker
        otherwise — the PR 6 fallback ladder carries it."""
        if self.batch_window <= 0:
            self.stats["envelopes"] += 1
            self.runtime.publish(reply_topic, payload)
            return
        queue = self._ship_queue.setdefault(reply_topic, [])
        queue.append(payload)
        if reply_topic not in self._ship_timers:
            self._ship_timers[reply_topic] = \
                self.runtime.event.add_oneshot_handler(
                    lambda: self._flush_ships(reply_topic),
                    self.batch_window)

    def _flush_ships(self, reply_topic: str) -> None:
        self._ship_timers.pop(reply_topic, None)
        payloads = self._ship_queue.pop(reply_topic, None)
        if not payloads:
            return
        self.stats["envelopes"] += 1
        if len(payloads) == 1:
            self.runtime.publish(reply_topic, payloads[0])
            return
        self.stats["batched_envelopes"] += 1
        self._batched_counter.inc(len(payloads))
        self.runtime.publish(reply_topic,
                             wire.encode_kv_batch(payloads))

    def stop(self) -> None:
        for reply_topic, timer in list(self._ship_timers.items()):
            self.runtime.event.remove_timer_handler(timer)
            self._ship_timers.pop(reply_topic, None)
            self._flush_ships(reply_topic)   # owed transfers ship now
        if self._flatout:
            self.runtime.event.remove_flatout_handler(self.decoder.pump)
        else:
            self.decoder.detach(self.runtime.event)
        super().stop()


def _chain_wire_blocks(cache, keys) -> list:
    """Cached chain blocks -> wire block payloads (host ndarrays)."""
    blocks = []
    for node in cache.nodes(keys):
        # block_rows reads the node's storage home — its own rows
        # in dense mode, the block POOL in paged mode (ISSUE 15:
        # harvest left the rows in pool blocks, so shipping is the
        # first and only host copy they ever pay)
        k_rows, v_rows = cache.block_rows(node)
        layers = []
        for k_leaf, v_leaf in zip(k_rows, v_rows):
            layers.append({"k": _to_host(k_leaf),
                           "v": _to_host(v_leaf)})
        blocks.append(layers)
    return blocks


def _prompt_cap(decoder) -> int:
    """The prompt length `decoder.submit` will actually admit (its
    tail-truncation cap).  Both sides of the split truncate with THIS
    formula before keying anything, so the harvested chain, the
    shipped tokens, and the decode-side probe always agree — a
    silently truncated prompt would otherwise key a chain the other
    side never looks up (review finding)."""
    if decoder.prefill_chunk:
        return decoder.max_seq - 1
    return min(decoder.max_seq - 1, decoder.prefill_buckets[-1])


def _to_host(leaf):
    """Device rows -> host ndarrays for the wire (int8 dicts leaf-wise;
    the bytes ship exactly as the donor decoder stored them)."""
    if isinstance(leaf, dict):
        return {"q": np.asarray(leaf["q"]), "s": np.asarray(leaf["s"])}
    return np.asarray(leaf)


def _copy_host(leaf):
    """Wire ndarrays -> OWNED host arrays in the cache storage layout.
    Deliberately NOT device_put here: installing a 576-token transfer
    as ~100 per-leaf device transfers on the event loop stalled decode
    rounds measurably (found live); the prefix-admit's concat ships
    each admitted chain as ONE transfer per layer instead, and only
    for chains actually admitted.  The copy drops the wire envelope's
    zero-copy views so a cached block never pins a whole received
    payload alive."""
    if isinstance(leaf, dict):
        return {"q": np.array(leaf["q"]), "s": np.array(leaf["s"])}
    return np.array(leaf)


class PrefillClient:
    """The decode-side of the split: remote prefill with a local
    fallback ladder.

    submit() routes the prompt to a discovered prefill runtime
    (deadline-aware, least-loaded under pressure), and on the
    KV-transfer reply installs the chain into the decode decoder's
    PrefixKVCache and submits the request — the prefix-admit path
    copies the chain into the slot with one scatter, so decode-pool
    prefill work shrinks to the ragged suffix.  Failures degrade, in
    order: retry against another candidate, then LOCAL prefill on the
    decode runtime itself.  Every rung of the ladder is counted;
    no rung drops the request.

    Single-threaded on the owning runtime's event engine, like the
    decoder it feeds."""

    def __init__(self, runtime, decoder, services_cache=None,
                 name: str = "disagg",
                 transfer_timeout: float = 5.0, retries: int = 1,
                 urgent_budget_s: float = 1.0,
                 min_remote_tokens: int | None = None,
                 registry=None):
        if decoder.prefix_cache is None and \
                not getattr(decoder, "paged", False):
            raise ValueError(
                "PrefillClient needs a decoder with a bound "
                "PrefixKVCache, or a paged decoder (the shipped KV "
                "has to land somewhere: cache chain or direct "
                "slot-table install)")
        self.runtime = runtime
        self.decoder = decoder
        # cache may be None on a paged decoder (ISSUE 15 satellite):
        # shipped KV then lands via install_shipped_blocks — pool
        # blocks aliased straight into the request's slot table, no
        # prefix cache in the loop
        self.cache = decoder.prefix_cache
        self.block_tokens = self.cache.block_tokens \
            if self.cache is not None else decoder.kv_block
        self.name = str(name)
        self.logger = get_logger(f"disagg.client.{name}")
        self.transfer_timeout = float(transfer_timeout)
        self.retries = max(0, int(retries))
        # prompts shorter than one block have nothing to ship — going
        # remote would pay a transfer RTT for zero cached tokens
        self.min_remote_tokens = int(min_remote_tokens) \
            if min_remote_tokens is not None \
            else self.block_tokens
        self._registry = registry or default_registry()
        self.router = DeadlineRouter(urgent_budget_s=urgent_budget_s,
                                     name=name,
                                     registry=self._registry)
        self.loads: dict[str, int] = {}     # topic_path -> in flight
        self._endpoints: dict[str, str | None] = {}
        self._pending: dict[str, dict] = {}
        self.reply_topic = \
            f"{runtime.topic_path}/0/kv.{uuid.uuid4().hex[:8]}"
        runtime.add_message_handler(self._on_reply, self.reply_topic,
                                    binary=True)
        self.stats = MirroredStats(
            {"transfers": 0, "transfer_bytes": 0, "installs": 0,
             "installed_blocks": 0, "handle_blocks": 0,
             "raw_blocks": 0, "retries": 0, "transfer_timeouts": 0,
             "transfer_corrupt": 0, "layout_mismatch": 0,
             "local_fallbacks": 0, "local_short": 0,
             "local_no_pool": 0, "local_cached": 0,
             "install_shed": 0, "direct_installs": 0,
             "batched_replies": 0, "chunk_installs": 0,
             "chunk_blocks": 0, "chunk_dropped": 0,
             "chunk_streamed": 0, "transfer_overlap_s": 0.0},
            metric="disagg_client_events_total",
            help="disaggregated serving client events by kind",
            registry=self._registry,
            skip=("transfer_bytes", "transfer_overlap_s"),
            labels={"client": name})
        self._transfer_seconds = self._registry.histogram(
            "disagg_transfer_seconds",
            "prefill request -> installed KV wall seconds",
            labels={"client": name})
        from collections import deque
        self.transfer_samples: deque = deque(maxlen=4096)
        self._cache_handler = None
        if services_cache is not None:
            self._services_cache = services_cache
            self._cache_handler = self._on_discovery
            # protocol AND role: a pipeline tagged role=prefill (the
            # PE role parameter tags its whole pipeline record) has no
            # `prefill` RPC — routing transfers at it would stall them
            # for a full timeout each (review finding)
            services_cache.add_handler(
                self._cache_handler,
                ServiceFilter(protocol=str(PROTOCOL_PREFILL),
                              tags=[role_tag(ROLE_PREFILL)]))

    # -- discovery ---------------------------------------------------------
    def _on_discovery(self, command, fields) -> None:
        if command == "add":
            self.loads.setdefault(fields.topic_path, 0)
            endpoint = ServiceTags.to_dict(fields.tags).get("peer")
            self._endpoints[fields.topic_path] = endpoint
            if endpoint and self.runtime.peer is not None:
                # pin the transfer path onto a direct channel: our
                # prefill requests to its /in, its KV replies to our
                # reply topic.  Broker stays the standing fallback.
                try:
                    self.runtime.peer.negotiate(
                        fields.topic_path, endpoint,
                        pin_topics=[f"{fields.topic_path}/in"],
                        reply_topics=[self.reply_topic])
                except Exception:
                    self.logger.exception(
                        "disagg %s: peer negotiation with %s failed; "
                        "broker path stays", self.name,
                        fields.topic_path)
        elif command == "remove":
            self.loads.pop(fields.topic_path, None)
            self._endpoints.pop(fields.topic_path, None)
            if self.runtime.peer is not None:
                self.runtime.peer.release(f"{fields.topic_path}/in")

    def add_candidate(self, topic_path: str,
                      endpoint: str | None = None) -> None:
        """Manual registration (tests, static fleets without a
        services cache)."""
        self.loads.setdefault(topic_path, 0)
        self._endpoints[topic_path] = endpoint

    # -- submit path -------------------------------------------------------
    def submit(self, request_id: str, prompt, max_new_tokens: int,
               callback, deadline: float | None = None,
               tenant: str | None = None, on_refused=None) -> bool:
        """Route one request through the split.  Returns True when the
        request is IN FLIGHT somewhere (remote transfer pending or
        locally submitted); False only when the decoder's own deadline
        admission refused a synchronous local submit (the caller owns
        that refusal, exactly like ContinuousDecoder.submit)."""
        # truncate with the DECODE decoder's own cap up front: the
        # probe below, the shipped tokens, and the eventual
        # decoder.submit must all key the same prompt (a decoder that
        # truncated AFTER the probe would never match the installed
        # chain)
        prompt = ([int(t) for t in prompt] or
                  [0])[-_prompt_cap(self.decoder):]
        tenant_key = str(tenant or "")
        # synchronous local rungs return the refusal to the CALLER
        # (notify=False): invoking on_refused too would signal one
        # shed twice (review finding)
        if len(prompt) < self.min_remote_tokens:
            self.stats["local_short"] += 1
            return self._local(request_id, prompt, max_new_tokens,
                               callback, deadline, tenant, on_refused,
                               notify=False)
        have = 0
        if self.cache is not None:
            _, have = self.cache.match(tenant_key, prompt)
            complete = (len(prompt) // self.block_tokens) * \
                self.block_tokens
            if have < complete and self.cache.tiered:
                # tiered KV (ISSUE 17): the routing probe doubles as
                # the promotion kick — host-resident chain blocks for
                # this prompt start re-landing while the transfer (or
                # local prefill) is still in flight
                self.cache.prefetch(tenant_key, prompt)
            if complete and have >= complete:
                # the decode side already holds the ENTIRE chain
                # (session KV, a repeated prompt): a remote hop would
                # ship zero bytes — prefix-admit locally, the cached
                # population.  A cacheless pool holds nothing between
                # requests, so have stays 0 there and every prompt
                # ships whole.
                self.stats["local_cached"] += 1
                return self._local(request_id, prompt, max_new_tokens,
                                   callback, deadline, tenant,
                                   on_refused, notify=False)
        remaining = None
        if deadline is not None:
            remaining = float(deadline) - time.monotonic()
        target = self.router.route(self.loads, remaining)
        if target is None:
            self.stats["local_no_pool"] += 1
            return self._local(request_id, prompt, max_new_tokens,
                               callback, deadline, tenant, on_refused,
                               notify=False)
        transfer_id = f"kv-{uuid.uuid4().hex[:12]}"
        entry = {
            "request_id": str(request_id), "prompt": prompt,
            "max_new": int(max_new_tokens), "callback": callback,
            "deadline": deadline, "tenant": tenant,
            "on_refused": on_refused, "attempts": 0,
            "started": time.perf_counter(),
            "trace": tracing.current_trace(), "target": target,
        }
        self._pending[transfer_id] = entry
        self._send(transfer_id, entry, target, have)
        return True

    def _send(self, transfer_id: str, entry: dict, target: str,
              have: int) -> None:
        entry["target"] = target
        entry["timer"] = self.runtime.event.add_oneshot_handler(
            lambda: self._transfer_expired(transfer_id),
            self.transfer_timeout)
        self.loads[target] = self.loads.get(target, 0) + 1
        self.stats["transfers"] += 1
        context = entry.get("trace")
        payload = wire.encode_envelope(
            "prefill",
            [transfer_id, self.reply_topic,
             str(entry["tenant"] or ""), str(int(have)),
             {"tokens": np.asarray(entry["prompt"], np.int32)}],
            trace=context.to_fields(self.runtime.event.clock.now())
            if context is not None else None)
        self.runtime.publish(f"{target}/in", payload)

    def _settle(self, transfer_id: str):
        entry = self._pending.pop(transfer_id, None)
        if entry is None:
            return None
        timer = entry.pop("timer", None)
        if timer is not None:
            self.runtime.event.remove_timer_handler(timer)
        target = entry.get("target")
        if target in self.loads:
            self.loads[target] = max(0, self.loads[target] - 1)
        return entry

    def _drop_chunks(self, entry: dict) -> None:
        """Forget a transfer's streamed-chunk progress (ISSUE 17).
        Cache-path installs stay — they are content-addressed and a
        retry's `have` probe reuses them — but direct pool blocks are
        owned by the stream and must not leak when it abandons."""
        entry.pop("chunk_next", None)
        entry.pop("chunk_first", None)
        entry.pop("chunk_base", None)
        ids = entry.pop("direct_ids", None)
        if ids:
            self.decoder.pool.release_blocks(
                ids, tenant=str(entry.get("tenant") or ""))

    # -- the fallback ladder ----------------------------------------------
    def _transfer_expired(self, transfer_id: str) -> None:
        entry = self._pending.get(transfer_id)
        if entry is None:
            return
        entry.pop("timer", None)
        target = entry.get("target")
        if target in self.loads:
            self.loads[target] = max(0, self.loads[target] - 1)
        self.stats["transfer_timeouts"] += 1
        if entry["attempts"] < self.retries:
            # rung 1: retry against ANOTHER candidate (the one that
            # timed out keeps its request dedup-able server-side; a
            # late duplicate transfer just re-confirms cached blocks)
            entry["attempts"] += 1
            others = {c: l for c, l in self.loads.items()
                      if c != target}
            remaining = None
            if entry["deadline"] is not None:
                remaining = float(entry["deadline"]) - time.monotonic()
            retry_target = self.router.route(others or self.loads,
                                             remaining)
            if retry_target is not None:
                self.stats["retries"] += 1
                self._drop_chunks(entry)   # the retry streams afresh
                have = 0
                if self.cache is not None:
                    _, have = self.cache.match(
                        str(entry["tenant"] or ""), entry["prompt"])
                self._send(transfer_id, entry, retry_target, have)
                return
        # rung 2: local prefill — counted, never dropped
        self._pending.pop(transfer_id, None)
        self._drop_chunks(entry)
        self.stats["local_fallbacks"] += 1
        self.logger.warning(
            "disagg %s: transfer %s to %s gave up after %d attempt(s); "
            "prefilling locally", self.name, transfer_id, target,
            entry["attempts"] + 1)
        self._local(entry["request_id"], entry["prompt"],
                    entry["max_new"], entry["callback"],
                    entry["deadline"], entry["tenant"],
                    entry["on_refused"])

    def _local(self, request_id, prompt, max_new, callback, deadline,
               tenant, on_refused, notify: bool = True) -> bool:
        """Local-prefill rung.  `notify` fires on_refused on a shed —
        True only on ASYNC paths (timeout fallback, reply install,
        teardown) where submit() has long returned; synchronous rungs
        return the refusal instead, so the caller is signalled exactly
        once either way."""
        accepted = self.decoder.submit(request_id, prompt, max_new,
                                       callback, deadline=deadline,
                                       tenant=tenant)
        if not accepted:
            self.stats["install_shed"] += 1
            if notify and on_refused is not None:
                on_refused(request_id)
        return accepted

    # -- KV admit (the reply path) -----------------------------------------
    def _on_reply(self, _topic, payload) -> None:
        try:
            command, params = wire.decode_envelope(payload)
        except wire.WireError as exc:
            # chaos truncation / foreign payload: drop it — the
            # transfer timer retries, then the ladder prefills locally
            self.stats["transfer_corrupt"] += 1
            self.logger.warning("disagg %s: corrupt KV transfer "
                                "dropped: %s", self.name, exc)
            return
        if command == wire.KV_BATCH_COMMAND:
            # coalesced same-destination transfers (ISSUE 15
            # satellite): unwrap and run each member through the SAME
            # validation + install path as a lone envelope — a corrupt
            # member fails alone, its siblings still land
            try:
                members = wire.kv_batch_members(command, params)
            except wire.WireError as exc:
                self.stats["transfer_corrupt"] += 1
                self.logger.warning(
                    "disagg %s: corrupt KV transfer batch dropped: %s",
                    self.name, exc)
                return
            self.stats["batched_replies"] += 1
            for member in members:
                try:
                    inner_command, inner_params = \
                        wire.decode_envelope(member)
                except wire.WireError as exc:
                    self.stats["transfer_corrupt"] += 1
                    self.logger.warning(
                        "disagg %s: corrupt batch member dropped: %s",
                        self.name, exc)
                    continue
                self._handle_transfer(member, inner_command,
                                      inner_params)
            return
        self._handle_transfer(payload, command, params)

    def _handle_transfer(self, payload, command, params) -> None:
        try:
            out = wire.validate_kv_transfer_params(command, params)
        except wire.WireError as exc:
            self.stats["transfer_corrupt"] += 1
            self.logger.warning("disagg %s: corrupt KV transfer "
                                "dropped: %s", self.name, exc)
            return
        if not out.get("final", True):
            # chunk-streamed member (ISSUE 17): install incrementally
            # WITHOUT settling — the final envelope still owes
            # first_token and the decode submit
            self._handle_chunk(payload, out)
            return
        entry = self._settle(out["transfer_id"])
        if entry is None:
            return              # late duplicate after timeout/fallback
        chunk_first = entry.get("chunk_first")
        # out["first_token"] is deliberately unused: the decode-side
        # suffix extend recomputes the first token, so greedy parity
        # never depends on donor state — the field is a wire-level
        # diagnostic (tests compare it against the local stream)
        elapsed = time.perf_counter() - entry["started"]
        self.stats["transfer_bytes"] += len(payload)
        self._transfer_seconds.observe(elapsed)
        # audited: deque(maxlen=4096) bounds this sample window
        self.transfer_samples.append(elapsed)
        tenant_key = str(entry["tenant"] or "")
        local_layout = self.cache.wire_layout() \
            if self.cache is not None else self.decoder.kv_wire_layout()
        if out["blocks"] and \
                tuple(str(f) for f in out["layout"]) != local_layout:
            self.stats["layout_mismatch"] += 1
            self.stats["local_fallbacks"] += 1
            self.logger.warning(
                "disagg %s: transfer %s layout %r does not match the "
                "decode cache %r; prefilling locally", self.name,
                out["transfer_id"], out["layout"], local_layout)
            self._drop_chunks(entry)
            self._local(entry["request_id"], entry["prompt"],
                        entry["max_new"], entry["callback"],
                        entry["deadline"], entry["tenant"],
                        entry["on_refused"])
            return
        blocks = self._landing_blocks(out["blocks"])
        direct_ids: list = []
        try:
            if self.cache is not None:
                installed = self.cache.install_chain(
                    tenant_key, out["tokens"], out["start_block"],
                    blocks)
            else:
                # direct slot-table install (ISSUE 15 satellite): the
                # cacheless decode pool lands the chain in pool blocks
                # and hands the ids to submit() for slot aliasing.
                # Streamed chunks already landed a contiguous prefix;
                # the final span must continue it exactly (ordered-
                # cursor guard) or the prefix alone is used.
                prior = entry.get("direct_ids") or []
                start = out["start_block"]
                if prior and entry.get("chunk_next") == start:
                    _, ids = self.decoder.install_shipped_blocks(
                        out["tokens"], start, blocks,
                        tenant=tenant_key)
                    direct_ids = prior + ids
                elif prior:
                    self.stats["chunk_dropped"] += 1
                    direct_ids = prior
                else:
                    if start != 0:
                        raise ValueError(
                            "direct install cannot start mid-chain "
                            f"(start_block={start}) with no streamed "
                            "prefix")
                    _, direct_ids = \
                        self.decoder.install_shipped_blocks(
                            out["tokens"], 0, blocks,
                            tenant=tenant_key)
                entry.pop("direct_ids", None)
                installed = len(direct_ids)
                self.stats["direct_installs"] += 1
        except (ValueError, TypeError, IndexError) as exc:
            # schema-legal but geometry-wrong blocks (wrong layer
            # count / head extents) are refused BEFORE any row lands —
            # a poisoned chain would wedge the decode pump at its next
            # hit.  Same ladder as a corrupt payload: prefill locally.
            self.stats["transfer_corrupt"] += 1
            self.stats["local_fallbacks"] += 1
            self.logger.warning(
                "disagg %s: transfer %s refused at install (%s); "
                "prefilling locally", self.name, out["transfer_id"],
                exc)
            self._drop_chunks(entry)
            self._local(entry["request_id"], entry["prompt"],
                        entry["max_new"], entry["callback"],
                        entry["deadline"], entry["tenant"],
                        entry["on_refused"])
            return
        self.stats["installs"] += 1
        self.stats["installed_blocks"] += installed
        # a streamed transfer's final start_block sits at the chunk
        # cursor, not the handle boundary — only blocks below the
        # stream's low-water mark crossed as handles
        handle = out["start_block"]
        base = entry.get("chunk_base")
        if base is not None:
            handle = min(handle, int(base))
        self.stats["handle_blocks"] += handle
        self.stats["raw_blocks"] += len(out["blocks"])
        if chunk_first is not None:
            # the stream began landing KV while the donor was still
            # prefilling: everything between the first chunk and this
            # final envelope was transfer time hidden behind compute
            self.stats["chunk_streamed"] += 1
            self.stats["transfer_overlap_s"] += \
                max(0.0, time.perf_counter() - chunk_first)
        trc = tracing.tracer
        if trc.enabled and entry.get("trace") is not None:
            trc.record("kv_transfer", entry["started"], elapsed,
                       context=entry["trace"], cat="disagg",
                       proc=self.name,
                       span_id=tracing.new_span_id(),
                       args={"bytes": len(payload),
                             "raw_blocks": len(out["blocks"]),
                             "handle_blocks": out["start_block"],
                             "installed": installed})
        # the decode-side submit: the prefix probe longest-matches the
        # just-installed chain (paged: ALIASES its pool blocks —
        # zero-copy), and only the ragged suffix prefills here.  A
        # cacheless pool instead hands the installed ids to the
        # request for direct slot-table aliasing.  Label "remote" so
        # TTFT sketches and journeys carry the population (ISSUE 14).
        with tracing.activate(entry.get("trace")):
            if self.cache is None:
                covered = installed * self.decoder.kv_block
                self._submit_installed(entry,
                                       kv_blocks=(covered, direct_ids))
            else:
                self._submit_installed(entry)

    def _handle_chunk(self, payload, out: dict) -> None:
        """Install one streamed chunk for a still-pending transfer
        (ISSUE 17).  Chunks are best-effort accelerant: any anomaly
        (gap, layout drift, install refusal) drops the CHUNK and lets
        the final envelope's full fallback ladder own correctness —
        a dropped chunk can shorten the streamed prefix, never poison
        the chain (cache installs are content-addressed; direct
        installs keep only a contiguous-from-zero prefix)."""
        transfer_id = out["transfer_id"]
        entry = self._pending.get(transfer_id)
        if entry is None:
            return          # late chunk after settle/timeout/fallback
        local_layout = self.cache.wire_layout() \
            if self.cache is not None else self.decoder.kv_wire_layout()
        if not out["blocks"] or \
                tuple(str(f) for f in out["layout"]) != local_layout:
            self.stats["chunk_dropped"] += 1
            return
        expected = entry.get("chunk_next")
        if expected is not None and out["start_block"] != expected:
            # ordered-cursor guard: a lost/corrupt sibling left a gap;
            # later chunks no longer extend the landed prefix
            self.stats["chunk_dropped"] += 1
            return
        if self.cache is None and expected is None and \
                out["start_block"] != 0:
            # a direct (cacheless) stream is only usable as a
            # contiguous-from-zero prefix
            self.stats["chunk_dropped"] += 1
            return
        try:
            if self.cache is not None:
                installed = self.cache.install_chain(
                    str(entry["tenant"] or ""), out["tokens"],
                    out["start_block"],
                    self._landing_blocks(out["blocks"]))
            else:
                _, ids = self.decoder.install_shipped_blocks(
                    out["tokens"], out["start_block"],
                    self._landing_blocks(out["blocks"]),
                    tenant=str(entry["tenant"] or ""))
                entry.setdefault("direct_ids", []).extend(ids)
                installed = len(ids)
        except (ValueError, TypeError, IndexError) as exc:
            self.stats["chunk_dropped"] += 1
            self.logger.warning(
                "disagg %s: streamed chunk for %s refused at install "
                "(%s); dropped", self.name, transfer_id, exc)
            return
        if "chunk_first" not in entry:
            entry["chunk_first"] = time.perf_counter()
            # the stream's low-water mark: blocks below it crossed as
            # handles, blocks at/above it as raw streamed bytes — the
            # final envelope's handle accounting keys on this
            entry["chunk_base"] = out["start_block"]
        entry["chunk_next"] = out["start_block"] + len(out["blocks"])
        self.stats["chunk_installs"] += 1
        self.stats["chunk_blocks"] += installed
        self.stats["raw_blocks"] += len(out["blocks"])
        self.stats["transfer_bytes"] += len(payload)
        # a streaming donor is demonstrably alive: restart the
        # transfer timeout per chunk so a long prompt's stream is not
        # killed mid-flight by a budget sized for one envelope
        timer = entry.pop("timer", None)
        if timer is not None:
            self.runtime.event.remove_timer_handler(timer)
            entry["timer"] = self.runtime.event.add_oneshot_handler(
                lambda: self._transfer_expired(transfer_id),
                self.transfer_timeout)

    def _landing_blocks(self, wire_blocks) -> list:
        if self.cache is not None and not self.cache.paged:
            # dense cache: owned host copies (per-leaf device_puts on
            # the event loop stalled decode rounds — PR 14 finding);
            # the admit-time concat ships one transfer per layer
            return [{"k": [_copy_host(layer["k"]) for layer in block],
                     "v": [_copy_host(layer["v"]) for layer in block]}
                    for block in wire_blocks]
        # paged landings (ISSUE 15) write the wire views straight
        # into pool blocks — ONE device scatter per layer, no host
        # copy in between: the transferred bytes land exactly once
        return [{"k": [layer["k"] for layer in block],
                 "v": [layer["v"] for layer in block]}
                for block in wire_blocks]

    def _submit_installed(self, entry: dict,
                          kv_blocks: tuple | None = None) -> None:
        accepted = self.decoder.submit(
            entry["request_id"], entry["prompt"], entry["max_new"],
            entry["callback"], deadline=entry["deadline"],
            tenant=entry["tenant"], prefill_label="remote",
            kv_blocks=kv_blocks)
        if not accepted:
            self.stats["install_shed"] += 1
            if kv_blocks is not None and kv_blocks[1]:
                # ownership never transferred: the shed request must
                # not leak its pre-installed pool blocks
                self.decoder.pool.release_blocks(
                    kv_blocks[1], tenant=str(entry["tenant"] or ""))
            if entry["on_refused"] is not None:
                entry["on_refused"](entry["request_id"])

    def handle_hit_rate(self) -> float:
        """Fraction of transferred chain blocks that crossed as
        handles instead of raw KV bytes (decode-held chains)."""
        total = self.stats["handle_blocks"] + self.stats["raw_blocks"]
        return self.stats["handle_blocks"] / total if total else 0.0

    def pending_count(self) -> int:
        return len(self._pending)

    def stop(self) -> None:
        for transfer_id in list(self._pending):
            entry = self._settle(transfer_id)
            if entry is not None:
                # teardown owes every in-flight request a local home
                self._drop_chunks(entry)
                self.stats["local_fallbacks"] += 1
                self._local(entry["request_id"], entry["prompt"],
                            entry["max_new"], entry["callback"],
                            entry["deadline"], entry["tenant"],
                            entry["on_refused"])
        if self._cache_handler is not None:
            self._services_cache.remove_handler(self._cache_handler)
            self._cache_handler = None
        if self.runtime.peer is not None:
            # this client's uuid reply topic must not be re-pinned on
            # later redials of the shared channel
            self.runtime.peer.unregister_reply_topic(self.reply_topic)
        self.runtime.remove_message_handler(self._on_reply,
                                            self.reply_topic)


class SessionMigrator:
    """Both halves of graceful-drain session KV migration (ISSUE 19).

    A retiring serving runtime's sessions — pinned prefix-cache chains
    plus their SessionTable records — ship to a drain destination so a
    migrated conversation's NEXT turn is a prefix hit there, not a full
    re-prefill.  One instance serves both roles over one binary topic
    ({runtime.topic_path}/migrate):

      source:  migrate(dest) offers each live session
               (wire.encode_kv_migrate: tokens the pinned chain covers
               + the table's history), and on the destination's ack
               ships the chain as ordinary chunk-streamed KV_TRANSFER
               envelopes — blocks the destination already holds
               (content-addressed) cross as handles, host-tier rows are
               promoted first (promote_for), and the done leg releases
               the local pin and table record;
      dest:    probes its cache for the offered chain, acks with its
               resident-block count, installs arriving chunks with the
               ordered-cursor guard, and on the final envelope re-pins
               the chain under the session handle, re-creates the table
               record, and sends done.

    Failure degrades, never corrupts: a timed-out transfer keeps the
    session at the source (crash re-materialization from the state
    plane still covers it), a shed destination table.create releases
    the freshly-taken pin and withholds done, and a layout/gap anomaly
    drops chunks — the destination then lands history-only and the
    first turn there re-prefills.  Single-threaded on the owning
    runtime's engine, like everything else in this plane."""

    def __init__(self, runtime, cache, table=None, name: str = "migrate",
                 chunk_blocks: int = 8, transfer_timeout: float = 5.0,
                 registry=None):
        if cache is None:
            raise ValueError("SessionMigrator needs a PrefixKVCache "
                             "(the pinned chains ARE the cargo)")
        self.runtime = runtime
        self.cache = cache
        self.table = table
        self.name = str(name)
        self.logger = get_logger(f"disagg.migrate.{name}")
        self.chunk_blocks = max(1, int(chunk_blocks))
        self.transfer_timeout = float(transfer_timeout)
        self._registry = registry or default_registry()
        self.topic = f"{runtime.topic_path}/migrate"
        runtime.add_message_handler(self._on_message, self.topic,
                                    binary=True)
        self._outbound: dict[str, dict] = {}     # source-role transfers
        self._inbound: dict[str, dict] = {}      # destination-role
        self._done_callback = None
        self.stats = MirroredStats(
            {"offers": 0, "received": 0, "acks": 0, "chunks": 0,
             "shipped_blocks": 0, "handle_blocks": 0,
             "installed_blocks": 0, "landed": 0, "migrated": 0,
             "refused": 0, "expired": 0, "dropped_chunks": 0,
             "corrupt": 0},
            metric="kv_migrate_events_total",
            help="session KV migration events by kind",
            registry=self._registry, labels={"migrator": self.name})

    # -- source role -------------------------------------------------------
    def migrate(self, dest_topic: str, on_done=None) -> int:
        """Offer every live session to the migrator at `dest_topic`
        (a peer's .topic).  Returns the number of offers sent;
        `on_done(self)` fires once when every offer has settled (done
        leg or timeout) — with zero sessions it fires immediately."""
        self._done_callback = on_done
        sessions = self.table.items() if self.table is not None else []
        sent = 0
        for tenant, sid, payload in sessions:
            history, kv_tokens = [], 0
            if isinstance(payload, dict):
                history = [int(t) for t in payload.get("history", ())]
                kv_tokens = max(0, int(payload.get("kv_tokens", 0)))
            # the pinned chain covers a history prefix (session_store
            # matched the history and recorded the hit length)
            tokens = history[:kv_tokens]
            transfer_id = f"mig-{uuid.uuid4().hex[:12]}"
            entry = {"tenant": str(tenant), "sid": str(sid),
                     "tokens": tokens, "history": history,
                     "dest": str(dest_topic)}
            entry["timer"] = self.runtime.event.add_oneshot_handler(
                lambda tid=transfer_id: self._expired(tid),
                self.transfer_timeout)
            self._outbound[transfer_id] = entry
            context = tracing.current_trace()
            self.runtime.publish(str(dest_topic), wire.encode_kv_migrate(
                transfer_id, str(tenant), str(sid), self.topic,
                np.asarray(tokens, np.int32),
                np.asarray(history, np.int32),
                trace=context.to_fields(self.runtime.event.clock.now())
                if context is not None else None))
            self.stats["offers"] += 1
            sent += 1
        if sent == 0:
            self._maybe_finished()
        return sent

    def _restart_timer(self, entry: dict, transfer_id: str,
                       inbound: bool = False) -> None:
        timer = entry.pop("timer", None)
        if timer is not None:
            self.runtime.event.remove_timer_handler(timer)
        entry["timer"] = self.runtime.event.add_oneshot_handler(
            lambda: self._expired(transfer_id, inbound=inbound),
            self.transfer_timeout)

    def _expired(self, transfer_id: str, inbound: bool = False) -> None:
        table = self._inbound if inbound else self._outbound
        entry = table.pop(transfer_id, None)
        if entry is None:
            return
        entry.pop("timer", None)
        self.stats["expired"] += 1
        self.logger.warning(
            "migrate %s: transfer %s (%s/%s) timed out; the session "
            "stays %s", self.name, transfer_id, entry["tenant"],
            entry["sid"], "unlanded" if inbound else "at the source")
        if inbound:
            # a half-streamed chain is cached (content-addressed, no
            # harm) but the session never landed — no pin, no record
            return
        self._maybe_finished()

    def _maybe_finished(self) -> None:
        if self._outbound or self._done_callback is None:
            return
        callback, self._done_callback = self._done_callback, None
        callback(self)

    def _settle(self, transfer_id: str, inbound: bool = False):
        table = self._inbound if inbound else self._outbound
        entry = table.pop(transfer_id, None)
        if entry is None:
            return None
        timer = entry.pop("timer", None)
        if timer is not None:
            self.runtime.event.remove_timer_handler(timer)
        return entry

    # -- wire dispatch -----------------------------------------------------
    def _on_message(self, _topic, payload) -> None:
        try:
            command, params = wire.decode_envelope(payload)
        except wire.WireError as exc:
            self.stats["corrupt"] += 1
            self.logger.warning("migrate %s: corrupt envelope dropped: "
                                "%s", self.name, exc)
            return
        try:
            if command == wire.KV_MIGRATE_COMMAND:
                self._on_offer(command, params)
            elif command == wire.KV_MIGRATE_ACK_COMMAND:
                self._on_ack(command, params)
            elif command == wire.KV_MIGRATE_DONE_COMMAND:
                self._on_done_leg(command, params)
            elif command == wire.KV_TRANSFER_COMMAND:
                self._on_transfer(command, params)
            else:
                self.stats["corrupt"] += 1
                self.logger.warning("migrate %s: unexpected command %r "
                                    "dropped", self.name, command)
        except wire.WireError as exc:
            self.stats["corrupt"] += 1
            self.logger.warning("migrate %s: malformed %s dropped: %s",
                                self.name, command, exc)

    # -- destination role --------------------------------------------------
    def _on_offer(self, command, params) -> None:
        out = wire.validate_kv_migrate_params(command, params)
        transfer_id = out["transfer_id"]
        tenant = out["tenant"]
        tokens = [int(t) for t in np.asarray(out["tokens"])]
        self.stats["received"] += 1
        have = 0
        if tokens:
            if self.cache.tiered:
                # an earlier migration/demotion may have left this
                # chain host-resident HERE — promote before probing so
                # the ack's have mark spares those blocks the wire.
                # promote_for uses admit semantics ((len-1)//block: the
                # last position's KV is recomputed at admit) but the
                # migrator moves WHOLE chains — extend by a sentinel so
                # the final block promotes too
                self.cache.promote_for(tenant, tokens + tokens[-1:])
            _, have = self.cache.match(tenant, tokens)
        block = self.cache.block_tokens
        entry = {"tenant": tenant, "sid": out["sid"], "tokens": tokens,
                 "history": [int(t) for t in np.asarray(out["history"])],
                 "reply_topic": out["reply_topic"],
                 "cursor": None, "installed": 0}
        self._inbound[transfer_id] = entry
        self._restart_timer(entry, transfer_id, inbound=True)
        context = tracing.current_trace()
        self.runtime.publish(
            out["reply_topic"],
            wire.encode_kv_migrate_reply(
                wire.KV_MIGRATE_ACK_COMMAND, transfer_id, have // block,
                trace=context.to_fields(self.runtime.event.clock.now())
                if context is not None else None))

    def _on_transfer(self, command, params) -> None:
        out = wire.validate_kv_transfer_params(command, params)
        transfer_id = out["transfer_id"]
        entry = self._inbound.get(transfer_id)
        if entry is None:
            return              # late chunk after timeout
        cache = self.cache
        installed = 0
        usable = not out["blocks"] or \
            tuple(str(f) for f in out["layout"]) == cache.wire_layout()
        if usable and out["blocks"] and entry["cursor"] is not None \
                and out["start_block"] != entry["cursor"]:
            # ordered-cursor guard: a lost sibling left a gap — later
            # chunks no longer extend the landed prefix
            usable = False
        if usable and out["blocks"]:
            try:
                installed = cache.install_chain(
                    entry["tenant"], out["tokens"], out["start_block"],
                    self._landing(out["blocks"]))
                entry["cursor"] = out["start_block"] + len(out["blocks"])
                entry["installed"] += installed
                self.stats["installed_blocks"] += installed
                ledger = getattr(cache, "_ledger", None)
                if ledger is not None and installed:
                    ledger.event("migrate_in", installed)
            except (ValueError, TypeError, IndexError) as exc:
                self.stats["dropped_chunks"] += 1
                self.logger.warning(
                    "migrate %s: transfer %s chunk refused at install "
                    "(%s); dropped", self.name, transfer_id, exc)
        elif out["blocks"]:
            self.stats["dropped_chunks"] += 1
        if not out["final"]:
            self._restart_timer(entry, transfer_id, inbound=True)
            return
        self._land(transfer_id, self._settle(transfer_id, inbound=True))

    def _land(self, transfer_id: str, entry: dict) -> None:
        """Final envelope arrived: pin the (partially or fully) landed
        chain under the session handle, re-create the table record, and
        send done.  A shed create withholds done — the source's timeout
        then keeps the session there instead of deleting the only
        surviving copy."""
        cache = self.cache
        tenant, sid = entry["tenant"], entry["sid"]
        leaf, kv_tokens = cache.session_store(tenant, sid,
                                              entry["history"])
        if self.table is not None and not self.table.create(
                tenant, sid, {"history": entry["history"],
                              "kv": leaf or "",
                              "kv_tokens": kv_tokens}):
            cache.session_release(tenant, sid)
            self.stats["refused"] += 1
            self.logger.warning(
                "migrate %s: transfer %s refused — destination table "
                "shed (%s/%s); withholding done", self.name,
                transfer_id, tenant, sid)
            return
        self.stats["landed"] += 1
        context = tracing.current_trace()
        self.runtime.publish(
            entry["reply_topic"],
            wire.encode_kv_migrate_reply(
                wire.KV_MIGRATE_DONE_COMMAND, transfer_id,
                entry["installed"],
                trace=context.to_fields(self.runtime.event.clock.now())
                if context is not None else None))

    def _landing(self, wire_blocks) -> list:
        if not self.cache.paged:
            # dense cache: owned host copies (see PrefillClient — the
            # admit-time concat device-puts once per layer)
            return [{"k": [_copy_host(layer["k"]) for layer in block],
                     "v": [_copy_host(layer["v"]) for layer in block]}
                    for block in wire_blocks]
        return [{"k": [layer["k"] for layer in block],
                 "v": [layer["v"] for layer in block]}
                for block in wire_blocks]

    # -- source role, reply legs -------------------------------------------
    def _on_ack(self, command, params) -> None:
        transfer_id, have_blocks = \
            wire.validate_kv_migrate_reply(command, params)
        entry = self._outbound.get(transfer_id)
        if entry is None:
            return
        self.stats["acks"] += 1
        cache = self.cache
        block = cache.block_tokens
        tenant, tokens = entry["tenant"], entry["tokens"]
        if cache.tiered and tokens:
            # demoted session rows must be pool-resident before
            # block_rows can ship them — sync whole-chain promotion
            # (the admit-semantics sentinel again: ship the final
            # block as well, not just the probe-relevant prefix)
            cache.promote_for(tenant, tokens + tokens[-1:])
        keys, hit = cache.match(tenant, tokens)
        start = min(max(0, int(have_blocks)), hit // block)
        end = hit // block
        self.stats["handle_blocks"] += start
        self.stats["shipped_blocks"] += end - start
        ledger = getattr(cache, "_ledger", None)
        if ledger is not None and end > start:
            ledger.event("migrate_out", end - start)
        context = tracing.current_trace()
        trace = context.to_fields(self.runtime.event.clock.now()) \
            if context is not None else None
        # chunk-streamed ship: every envelope carries the full token
        # list (install_chain re-keys from it), blocks in chunk_blocks
        # strides; the final flag rides the last envelope — always
        # sent, even with zero blocks to move, because it is what
        # triggers the destination's land
        cursor = start
        while True:
            upto = min(end, cursor + self.chunk_blocks)
            final = upto >= end
            self.runtime.publish(entry["dest"], wire.encode_kv_transfer(
                transfer_id, tenant, tokens, cursor, block,
                cache.wire_layout(),
                _chain_wire_blocks(cache, keys[cursor:upto]),
                trace=trace, final=final))
            self.stats["chunks"] += 1
            cursor = upto
            if final:
                break
        self._restart_timer(entry, transfer_id)

    def _on_done_leg(self, command, params) -> None:
        transfer_id, _installed = \
            wire.validate_kv_migrate_reply(command, params)
        entry = self._settle(transfer_id)
        if entry is None:
            return
        # the destination owns the session now: drop the local pin and
        # the table record (its demotion hook must NOT fire — remove,
        # not demote)
        self.cache.session_release(entry["tenant"], entry["sid"])
        if self.table is not None:
            self.table.remove(entry["tenant"], entry["sid"],
                              reason="migrated")
        self.stats["migrated"] += 1
        self._maybe_finished()

    def pending_count(self) -> int:
        return len(self._outbound) + len(self._inbound)

    def stop(self) -> None:
        for transfer_id in list(self._outbound):
            self._settle(transfer_id)       # sessions stay local
        for transfer_id in list(self._inbound):
            self._settle(transfer_id, inbound=True)
        self.runtime.remove_message_handler(self._on_message, self.topic)


def two_pool_autoscalers(runtime, prefill_manager, decode_manager,
                         prefill_policy=None, decode_policy=None,
                         interval: float = 2.0,
                         topic_filter: str | None = None):
    """Instantiate the PR 9 autoscaler once per pool, each armed with
    ITS pool's signals (ISSUE 14): the prefill pool scales on prefill
    queue depth (the TTFT backlog — only prefill runtimes publish the
    gauge), the decode pool on fleet-merged ITL p95 (only multi-token
    generation observes ITL; a max_new=1 prefill decoder never does).
    Both pools subscribe the same namespace snapshots, so signal
    isolation comes from arming ONLY families the other pool cannot
    emit — the default worst-of-process signals (mailbox, batch wait,
    hop p95) are disarmed for both, or a prompt burst backlogging the
    prefill runtimes would grow the DECODE pool through their batch
    gauges (review finding).  Returns (prefill_autoscaler,
    decode_autoscaler) — two independent scale loops over two
    independent LifeCycleManagers."""
    from .autoscaler import Autoscaler, ScalePolicy
    prefill_policy = prefill_policy or ScalePolicy(
        prefill_queue_up=8.0, prefill_queue_down=1.0,
        mailbox_depth_up=float("inf"), hop_p95_up=float("inf"),
        batch_wait_up=float("inf"), queue_depth_up=float("inf"))
    decode_policy = decode_policy or ScalePolicy(
        itl_p95_up=0.05, itl_p95_down=0.005,
        mailbox_depth_up=float("inf"), hop_p95_up=float("inf"),
        batch_wait_up=float("inf"), queue_depth_up=float("inf"))
    prefill = Autoscaler(runtime, "prefill-pool",
                         manager=prefill_manager,
                         policy=prefill_policy, interval=interval,
                         topic_filter=topic_filter)
    decode = Autoscaler(runtime, "decode-pool", manager=decode_manager,
                        policy=decode_policy, interval=interval,
                        topic_filter=topic_filter)
    return prefill, decode


class DisaggHarness:
    """A complete two-pool serving plane in one process: registrar +
    peer-enabled prefill and decode runtimes over a MemoryBroker and
    one (real-clock) EventEngine.  The harness behind the
    lat_llama_disagg_* bench rung, scripts/disagg_smoke.py, and the
    chaos tests; `disagg=False` builds the colocated A/B — the SAME
    decode decoder and cache, no prefill pool, no client."""

    def __init__(self, params, config, *, disagg: bool = True,
                 block_tokens: int = 16, max_slots: int = 8,
                 prefill_slots: int = 4, steps_per_sync: int = 4,
                 prefill_buckets=(64,), prefill_chunk: int | None = None,
                 cache_mb: int = 512, decoder_opts: dict | None = None,
                 fault_plan=None, transfer_timeout: float = 5.0,
                 retries: int = 1, batch_window: float = 0.0,
                 chunk_stream: bool | None = None, registry=None):
        from .event import EventEngine
        from .registrar import Registrar
        from .serving import ContinuousDecoder, PrefixKVCache
        from .share import ServicesCache
        from .transport.memory import MemoryBroker, MemoryMessage
        from .process import ProcessRuntime

        self.engine = EventEngine()
        self.broker = MemoryBroker()
        self.disagg = bool(disagg)
        self._registry = registry or default_registry()

        def make_rt(name):
            def factory(on_message, lwt_topic, lwt_payload, lwt_retain):
                return MemoryMessage(
                    on_message=on_message, broker=self.broker,
                    lwt_topic=lwt_topic, lwt_payload=lwt_payload,
                    lwt_retain=lwt_retain, client_id=name)
            return ProcessRuntime(name=name, engine=self.engine,
                                  transport_factory=factory).initialize()

        self.registrar_rt = make_rt("disagg_reg")
        self.registrar = Registrar(self.registrar_rt)
        opts = dict(decoder_opts or {})

        self.decode_rt = make_rt("disagg_decode")
        self.decode_rt.enable_peer()
        self.cache = PrefixKVCache(
            block_tokens=int(block_tokens),
            max_bytes=int(cache_mb) << 20,
            name="disagg.decode", registry=self._registry)
        self.decoder = ContinuousDecoder(
            params, config, max_slots=int(max_slots),
            prefill_buckets=tuple(prefill_buckets),
            steps_per_sync=int(steps_per_sync),
            prefill_chunk=prefill_chunk, name="disagg.decode",
            prefix_cache=self.cache, registry=self._registry, **opts)
        # drive the pumps FLAT-OUT (once per engine step), not on a
        # periodic timer: a 2 ms timer against ~10 ms CPU rounds makes
        # the engine's timer catch-up loop replay the pump dozens of
        # times per step and STARVE the message queues (transfers
        # crawled while decode spun — found live), while a slow timer
        # idles the decoder and hides the very prefill interference
        # this harness measures.  Flat-out = saturated decode AND one
        # queue drain per step, the closest one engine gets to two
        # busy hosts.
        self.engine.add_flatout_handler(self.decoder.pump)

        self.prefill_rt = None
        self.prefill = None
        self.client = None
        if self.disagg:
            self.prefill_rt = make_rt("disagg_prefill")
            self.prefill_rt.enable_peer(fault_plan=fault_plan)
            self.prefill = PrefillRuntime(
                self.prefill_rt, "disagg_prefill",
                params=params, config=config,
                block_tokens=int(block_tokens), cache_mb=cache_mb,
                max_slots=int(prefill_slots),
                prefill_buckets=tuple(prefill_buckets),
                prefill_chunk=prefill_chunk, decoder_opts=opts,
                pump_period=0, batch_window=batch_window,
                chunk_stream=chunk_stream, registry=self._registry)
            cache = ServicesCache(self.decode_rt)
            self.client = PrefillClient(
                self.decode_rt, self.decoder, services_cache=cache,
                name="disagg", transfer_timeout=transfer_timeout,
                retries=retries, registry=self._registry)
            self._services_cache = cache

    # -- driving ------------------------------------------------------------
    def wait_discovered(self, timeout: float = 10.0) -> bool:
        """Block (stepping the engine) until the client can see the
        prefill pool; True in colocated mode."""
        if not self.disagg:
            return True
        return self.engine.run_until(lambda: bool(self.client.loads),
                                     timeout=timeout)

    def submit(self, request_id, prompt, max_new, callback,
               tenant: str = "", deadline=None):
        if self.client is not None:
            return self.client.submit(request_id, prompt, max_new,
                                      callback, deadline=deadline,
                                      tenant=tenant)
        return self.decoder.submit(request_id, prompt, max_new,
                                   callback, deadline=deadline,
                                   tenant=tenant)

    def run_until(self, predicate, timeout: float = 30.0) -> bool:
        return self.engine.run_until(predicate, timeout=timeout)

    def measure(self, window: float = 6.0, streams: int = 6,
                stream_prompt: int = 12, stream_new: int = 24,
                burst: int = 4, burst_prompt: int = 288,
                burst_new: int = 4, burst_every: float = 1.5,
                seed: int = 11) -> dict:
        """The two-pool workload behind the lat_llama_disagg_* rung
        and scripts/disagg_smoke.py: `streams` closed-loop decode
        streams (short prompts, long generations — pure token flow,
        tenant "stream") run the whole time; the second half ADDS a
        concurrent cold-prefill burst (`burst` long random prompts
        every `burst_every` s, tenant "burst").  Reports the decode
        streams' ITL p95 per phase from the tenant-filtered mergeable
        sketches — in colocated mode the burst's chunk extends ride
        the decode rounds and dilate it; disaggregated, the burst
        prefills on the prefill pool and only the suffix + one
        scatter touch the decode decoder.  Also reports transfer
        cost/volume, handle-hit rate, fallback counts, and a
        zero-lost accounting (submitted == completed after drain)."""
        rng = np.random.default_rng(seed)
        vocab = self.decoder.config.vocab
        state = {"stop": False, "stream_done": 0, "burst_done": 0,
                 "stream_posted": 0, "burst_posted": 0, "seq": 0}
        # bursts share a seeded "system prompt" prefix (half the
        # prompt) with a unique tail: after the first burst's harvest
        # the decode side holds the prefix chain, so later transfers
        # ship those blocks as HANDLES — the rung's handle-hit surface
        shared_prefix = rng.integers(
            1, vocab, size=burst_prompt // 2).tolist()

        def post_stream(i):
            state["seq"] += 1
            state["stream_posted"] += 1
            prompt = rng.integers(1, vocab,
                                  size=stream_prompt).tolist()

            def on_done(_rid, _tokens):
                state["stream_done"] += 1
                if not state["stop"]:
                    post_stream(i)

            self.submit(f"st{i}.{state['seq']}", prompt, stream_new,
                        on_done, tenant="stream")

        def on_burst_done(_rid, _tokens):
            state["burst_done"] += 1

        def post_burst(count=None):
            for _ in range(count or burst):
                state["seq"] += 1
                state["burst_posted"] += 1
                prompt = shared_prefix + rng.integers(
                    1, vocab,
                    size=burst_prompt - len(shared_prefix)).tolist()
                self.submit(f"bu{state['seq']}", prompt, burst_new,
                            on_burst_done, tenant="burst")

        # warmup: every compile variant (stream admit widths, burst
        # chunk extends, prefix-copy widths, transfer machinery) runs
        # once before anything is measured — including the odd burst
        # widths (a full burst AND a lone prompt)
        for i in range(streams):
            post_stream(i)
        post_burst()
        post_burst(1)
        # gate on the BURST completions specifically: the streams
        # complete quickly and keep resubmitting, so a combined count
        # would declare warm while the burst prompts (and their
        # compile variants / first transfers) are still in flight —
        # measured, found live as a 34 s "transfer p50"
        self.run_until(
            lambda: state["burst_done"] >= burst + 1 and
            state["stream_done"] >= streams, timeout=600.0)
        # second burst wave: the shared prefix is cached now, so this
        # compiles the prefix-hit copy/extend variants (and, disagg,
        # the handle-shipping path) BEFORE the measured window
        post_burst()
        self.run_until(
            lambda: state["burst_done"] >= 2 * burst + 1,
            timeout=600.0)
        self.decoder.clear_slo_sketches()
        self.decoder.ttft_samples.clear()
        self.decoder.itl_samples.clear()
        self.decoder.gap_samples.clear()

        def stall_p95():
            samples = sorted(self.decoder.gap_samples)
            self.decoder.gap_samples.clear()
            if not samples:
                return None
            return round(
                samples[int(0.95 * (len(samples) - 1))] * 1000.0, 3)

        deadline = time.perf_counter() + window / 2.0
        self.run_until(lambda: time.perf_counter() >= deadline,
                       timeout=window + 120.0)
        baseline = self.decoder.slo_sketch_stats(tenant="stream")
        baseline_stall = stall_p95()
        base_done = state["stream_done"]
        self.decoder.clear_slo_sketches()

        timer = self.engine.add_timer_handler(post_burst, burst_every)
        deadline = time.perf_counter() + window / 2.0
        self.run_until(lambda: time.perf_counter() >= deadline,
                       timeout=window + 120.0)
        self.engine.remove_timer_handler(timer)
        state["stop"] = True
        drained = self.run_until(
            lambda: self.decoder.idle and
            (self.client is None or self.client.pending_count() == 0),
            timeout=180.0)
        burst_phase = self.decoder.slo_sketch_stats(tenant="stream")
        posted = state["stream_posted"] + state["burst_posted"]
        done = state["stream_done"] + state["burst_done"]
        out = {
            "itl_p95_baseline_ms": baseline["itl_p95_ms"],
            "itl_p50_baseline_ms": baseline["itl_p50_ms"],
            "itl_p95_burst_ms": burst_phase["itl_p95_ms"],
            "itl_p50_burst_ms": burst_phase["itl_p50_ms"],
            # worst inter-sync stall per request (the number prefill
            # interference inflates most directly — ITL means dilute
            # a stalled round across the whole generation)
            "stall_p95_baseline_ms": baseline_stall,
            "stall_p95_burst_ms": stall_p95(),
            "stream_completions": state["stream_done"],
            "stream_completions_baseline": base_done,
            "burst_completions": state["burst_done"],
            "posted": posted, "completed": done,
            "lost": posted - done, "drained": bool(drained),
        }
        if self.client is not None:
            stats = self.client.stats
            samples = sorted(self.client.transfer_samples)
            out.update({
                "transfers": stats["transfers"],
                "transfer_bytes": stats["transfer_bytes"],
                "transfer_p50_ms": round(
                    samples[len(samples) // 2] * 1000.0, 3)
                if samples else None,
                "transfer_p95_ms": round(
                    samples[int(0.95 * (len(samples) - 1))] * 1000.0,
                    3) if samples else None,
                "handle_hit_rate": round(
                    self.client.handle_hit_rate(), 4),
                "local_fallbacks": stats["local_fallbacks"],
                "install_shed": stats["install_shed"],
                # chunk streaming (ISSUE 17): how many transfers
                # overlapped the donor's prefill compute, and how much
                # transfer wall time that overlap hid
                "chunk_streamed": stats["chunk_streamed"],
                "chunk_installs": stats["chunk_installs"],
                "chunk_dropped": stats["chunk_dropped"],
                "transfer_overlap_s": round(
                    stats["transfer_overlap_s"], 4),
            })
        return out

    def kill_prefill(self) -> None:
        """Chaos: the prefill pool dies mid-stream (process crash —
        LWT removes its records, channels collapse).  In-flight
        transfers ride the client's fallback ladder."""
        if self.prefill_rt is not None:
            self.prefill_rt.terminate(graceful=False)
            self.prefill_rt = None
            self.prefill = None

    def stop(self) -> None:
        if self.client is not None:
            self.client.stop()
        # drain decoder work owed to callbacks before teardown
        if self.prefill is not None:
            self.prefill.stop()
        self.engine.remove_flatout_handler(self.decoder.pump)
        if self.prefill_rt is not None:
            self.prefill_rt.terminate()
        self.decode_rt.terminate()
        self.registrar_rt.terminate()
