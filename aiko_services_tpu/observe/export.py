# Telemetry export: Prometheus text, Chrome trace events, live publish.
#
#   * render_prometheus — the registry snapshot in Prometheus text
#     exposition format (scrape it from a file, a debug endpoint, or
#     the published control-plane snapshot);
#   * chrome_trace / dump_chrome_trace — the Tracer's span buffer as a
#     Chrome trace-event JSON document (load in Perfetto / about:tracing
#     to see a frame's hops, retries, and serving spans on a timeline);
#   * MetricsPublisher — periodic retained snapshot on a control-plane
#     topic ({topic_path}/0/metrics, beside the process state topic), so
#     the dashboard's metrics pane and any late-joining scraper see the
#     latest numbers without asking.

from __future__ import annotations

import json
import random
import time
import zlib

from .metrics import Histogram, MetricsRegistry, default_registry
from .sketch import Sketch
from .tracing import Tracer, tracer as _global_tracer

__all__ = [
    "render_prometheus", "render_snapshot_prometheus", "chrome_trace",
    "dump_chrome_trace", "MetricsPublisher", "METRICS_TOPIC_SUFFIX",
    "parse_retained_json", "series_key", "series_quantile",
]


def parse_retained_json(payload, require_key: str | None = None):
    """Decode one retained control-plane JSON payload (metrics
    snapshot, alert record): bytes-tolerant, returns the dict or None
    on any malformed input — a bad retained record must never fail a
    subscriber.  `require_key` additionally rejects documents missing
    that key.  The ONE decode shared by every snapshot/alert consumer
    (HealthAggregator, Autoscaler, Recorder, Dashboard, metrics_dump),
    so framing changes have a single seam."""
    try:
        if isinstance(payload, (bytes, bytearray)):
            payload = payload.decode("utf-8")
        document = json.loads(payload)
    except Exception:
        return None
    if not isinstance(document, dict):
        return None
    if require_key is not None and require_key not in document:
        return None
    return document

METRICS_TOPIC_SUFFIX = "0/metrics"


def series_key(name: str, labels: dict) -> str:
    """Display key 'name{k=v,...}' for one snapshot series — the shared
    flattening used by the soak report and the dashboard pane (plain
    join, no escaping; Prometheus exposition has its own _label_text)."""
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}" if inner else name


def series_quantile(series: dict, q: float) -> float:
    """Approximate quantile from one snapshot histogram series
    (bounds/counts/count as emitted by MetricsRegistry.snapshot()),
    mirroring Histogram.quantile: the upper bound of the bucket holding
    the q-th observation; diagnostic-grade."""
    count = series.get("count", 0)
    bounds = series.get("bounds") or []
    if not count or not bounds:
        return 0.0
    target = q * count
    running = 0
    for index, bucket_count in enumerate(series.get("counts", [])):
        running += bucket_count
        if running >= target:
            return bounds[min(index, len(bounds) - 1)]
    return bounds[-1]


def _escape(value: str) -> str:
    return str(value).replace("\\", r"\\").replace("\n", r"\n") \
        .replace('"', r'\"')


def _label_text(labels: dict, extra: dict | None = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    inner = ",".join(f'{key}="{_escape(value)}"'
                     for key, value in sorted(merged.items()))
    return "{" + inner + "}"


def _format_value(value) -> str:
    if isinstance(value, float):
        return repr(value)
    return str(value)


def render_prometheus(registry: MetricsRegistry | None = None) -> str:
    """The registry in Prometheus text exposition format (v0.0.4)."""
    return render_snapshot_prometheus(
        (registry or default_registry()).snapshot())


def render_snapshot_prometheus(snapshot: dict,
                               extra_labels: dict | None = None) -> str:
    """One already-captured MetricsRegistry.snapshot() document as
    Prometheus text exposition.  `extra_labels` merge into every
    series — the metrics_dump CLI stamps the publishing process's
    topic_path so a fleet-wide scrape stays per-process attributable."""
    lines: list[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        if entry.get("help"):
            lines.append(f"# HELP {name} {entry['help']}")
        kind = entry.get("type", "gauge")
        # sketches render as Prometheus summaries (quantile labels) —
        # "sketch" is not a text-exposition type
        lines.append(f"# TYPE {name} "
                     f"{'summary' if kind == 'sketch' else kind}")
        for series in entry.get("series", []):
            labels = {**series.get("labels", {}),
                      **(extra_labels or {})}
            if entry.get("type") == "histogram":
                cumulative = 0
                for bound, count in zip(series.get("bounds", ()),
                                        series.get("counts", ())):
                    cumulative += count
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_text(labels, {'le': repr(bound)})} "
                        f"{cumulative}")
                lines.append(
                    f"{name}_bucket{_label_text(labels, {'le': '+Inf'})} "
                    f"{series.get('count', 0)}")
                lines.append(f"{name}_sum{_label_text(labels)} "
                             f"{_format_value(series.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_text(labels)} "
                             f"{series.get('count', 0)}")
            elif entry.get("type") == "sketch":
                # Prometheus has no sketch type: expose as a summary
                # (quantile labels) so scrapers get readable numbers;
                # the MERGEABLE form lives in the JSON snapshot, not
                # this lossy text view
                sketch = Sketch.from_dict(series)
                for q in (0.5, 0.95, 0.99):
                    value = sketch.quantile(q) if sketch else None
                    if value is not None:
                        lines.append(
                            f"{name}"
                            f"{_label_text(labels, {'quantile': str(q)})}"
                            f" {_format_value(value)}")
                lines.append(f"{name}_sum{_label_text(labels)} "
                             f"{_format_value(series.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_text(labels)} "
                             f"{series.get('count', 0)}")
            else:
                lines.append(f"{name}{_label_text(labels)} "
                             f"{_format_value(series.get('value', 0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def chrome_trace(trace_source: Tracer | None = None) -> dict:
    """The tracer's span buffer as a Chrome trace-event document
    (Perfetto-loadable JSON: complete "X" events, µs timestamps, one
    pid per recording process name, trace/span ids in args)."""
    source = trace_source or _global_tracer
    pids: dict[str, int] = {}
    events: list[dict] = []
    for span in list(source.spans):
        proc = span.proc or "aiko"
        pid = pids.get(proc)
        if pid is None:
            pid = pids[proc] = len(pids) + 1
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": proc}})
        args = {"trace_id": span.trace_id, "span_id": span.span_id,
                "parent_id": span.parent_id}
        args.update(span.args)
        events.append({
            "name": span.name, "cat": span.cat or "span", "ph": "X",
            "ts": round(span.ts * 1e6, 3),
            "dur": max(round(span.dur * 1e6, 3), 0.001),
            "pid": pid, "tid": 1, "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome_trace(pathname, trace_source: Tracer | None = None) -> str:
    """Write the Chrome trace-event document to `pathname`."""
    document = chrome_trace(trace_source)
    with open(pathname, "w", encoding="utf-8") as f:
        json.dump(document, f)
    return str(pathname)


class MetricsPublisher:
    """Periodic retained metrics snapshots on a control-plane topic.

    Publishes {"process", "topic_path", "time", "snapshot"} as JSON to
    {runtime.topic_path}/0/metrics every `interval` seconds (engine
    timers, so virtual-clock tests drive it deterministically).
    Retained by default: a dashboard opening the pane later still sees
    the last snapshot, like the process state topic.

    Interval JITTER (ISSUE 12): a fleet of publishers all constructed
    at process start with the same interval synchronizes into periodic
    broker bursts — every runtime serializes its whole registry in the
    same engine tick.  With `jitter` > 0 each publish reschedules
    itself as a oneshot at interval × (1 ± jitter), drawn from a
    SEEDED generator (seed defaults to a hash of the topic, so a
    process's schedule is reproducible run-to-run while distinct
    topics decorrelate).  Default 0: windowed-delta tests pin exact
    cadence; FLEET contexts (the bench wire runtimes, scaled soaks)
    arm it.  The publish cost itself is observable:
    `metrics_publish_seconds` gauge (serialize + publish wall)."""

    def __init__(self, runtime, interval: float = 5.0,
                 topic: str | None = None,
                 registry: MetricsRegistry | None = None,
                 retain: bool = True, jitter: float = 0.0,
                 jitter_seed: int | None = None):
        self.runtime = runtime
        self.registry = registry or default_registry()
        self.topic = topic or \
            f"{runtime.topic_path}/{METRICS_TOPIC_SUFFIX}"
        self.retain = retain
        self.interval = float(interval)
        self.jitter = max(0.0, min(float(jitter), 0.9))
        self._rng = random.Random(
            jitter_seed if jitter_seed is not None
            else zlib.crc32(self.topic.encode("utf-8")))
        # labelled by the runtime's NAME (bounded: a handful of
        # runtimes per process) — two publishers sharing the process
        # registry must not overwrite each other's cost reading
        self._cost_gauge = self.registry.gauge(
            "metrics_publish_seconds",
            "wall seconds the last snapshot publish cost "
            "(serialize + publish)",
            labels={"publisher": str(getattr(runtime, "name", None)
                                     or "metrics")})
        self._timer = None
        self._stopped = False
        if self.jitter:
            # jittered publishers re-arm a ONESHOT per publish (each
            # delay drawn fresh); unjittered ones keep the periodic
            # timer, whose heap reschedule (due += period) is EXACT —
            # the windowed-delta tests pin that cadence
            self._schedule()
        else:
            self._timer = runtime.event.add_timer_handler(
                self.publish_now, self.interval)

    def _next_delay(self) -> float:
        return self.interval * (
            1.0 + self._rng.uniform(-self.jitter, self.jitter))

    def _schedule(self) -> None:
        self._timer = self.runtime.event.add_oneshot_handler(
            self._tick, self._next_delay())

    def _tick(self) -> None:
        self._timer = None
        try:
            self.publish_now()
        finally:
            if not self._stopped:
                self._schedule()

    def publish_now(self) -> None:
        started = time.perf_counter()
        document = {
            "process": self.runtime.name,
            "topic_path": self.runtime.topic_path,
            "time": self.runtime.event.clock.now(),
            "snapshot": self.registry.snapshot(),
        }
        self.runtime.publish(self.topic,
                             json.dumps(document, default=str),
                             retain=self.retain)
        self._cost_gauge.set(time.perf_counter() - started)

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self.runtime.event.remove_timer_handler(self._timer)
            self._timer = None
