# observe: the telemetry layer — metrics registry, distributed tracing
# with deadline propagation, and exporters (ISSUE 5).
#
# Near-leaf on purpose: transport, event, and pipeline all record into
# this package, so it must sit BELOW them in the import graph — the
# only framework import allowed here is utils (itself a leaf).

from .metrics import (                                      # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, MirroredStats, Sketch,
    DEFAULT_LATENCY_BUCKETS, default_registry, log_buckets,
)
from .sketch import merge_sketches                          # noqa: F401
from .tracing import (                                      # noqa: F401
    TRACE_MARKER, SpanRecord, TraceContext, Tracer, activate,
    current_trace, new_trace, tracer,
)
from .export import (                                       # noqa: F401
    METRICS_TOPIC_SUFFIX, MetricsPublisher, chrome_trace,
    dump_chrome_trace, render_prometheus, render_snapshot_prometheus,
    series_key, series_quantile,
)
from .series import (                                       # noqa: F401
    ALERT_TOPIC_PREFIX, HealthAggregator, HistogramSeries, SLORule,
    ScalarSeries, SeriesStore, SketchSeries, parse_selector,
)
from .journey import (                                      # noqa: F401
    JourneyLog, RequestJourney, note_admission, take_admission_note,
    tenant_slo_rows,
)
from .ledger import (                                       # noqa: F401
    KVMemoryLedger, assert_ledger_clean, seed_ledger_leak,
)
from .profiler import PhaseProfiler, arm_trace              # noqa: F401
from .flight import (                                       # noqa: F401
    DumpOnAlert, FLIGHT_TOPIC_SUFFIX, FlightLogHandler, FlightRecorder,
)
