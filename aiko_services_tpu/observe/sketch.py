# Mergeable quantile sketches (ISSUE 12).
#
# The latency surfaces the runtime kept so far cannot answer a fleet
# question: Histogram's fixed log-spaced buckets give a per-process
# quantile whose error depends on where the bucket boundaries happened
# to fall, and two processes' histograms only combine when their bucket
# families match exactly — so every "fleet p95" before this module was
# really worst-of-per-process.  This module is a DDSketch-style
# relative-error sketch (Masson et al., VLDB'19):
#
#   * values land in logarithmic buckets index = ceil(log_gamma(v))
#     with gamma = (1+alpha)/(1-alpha), so EVERY reported quantile is
#     within relative error alpha of the true sample quantile —
#     alpha = 0.01 by default, well inside the 2% the bench artifact
#     promises;
#   * two sketches with the same gamma MERGE by adding bucket counts —
#     exactly (merge(A, B) and sketch(A ∪ B) are the same object), and
#     the operation is associative and commutative, so fleet-wide
#     quantiles come from merging every runtime's windowed sketch
#     instead of max-ing their per-process numbers;
#   * the bucket map is BOUNDED (`max_bins`): past the cap the lowest
#     buckets collapse into one, which degrades only the quantiles
#     below the collapsed mass — the tail the SLO rules watch keeps its
#     guarantee (standard DDSketch collapsing);
#   * each sketch retains a top-k ring of WORST exemplars — (value,
#     exemplar id, seq) with the id a trace id — so a fleet-level "ttft
#     p95 breached" alert can name the actual requests behind the
#     number (metrics → traces, the ISSUE 12 closed loop).  `seq` is
#     the sketch's observation count at insert time: a windowed reader
#     who knows the window-start count keeps only exemplars observed
#     inside the window, with no clock comparison across processes.
#
# Serialization is a plain JSON-able dict (`to_dict`/`from_dict`) —
# the retained {topic}/0/metrics snapshot schema carries it verbatim,
# and observe/series.py reconstructs windowed delta sketches from
# snapshot pairs the same way HistogramSeries delta-counts do.
#
# Like the rest of the registry (observe/metrics.py), observe() is a
# lock-free hot path: dict increments under the GIL, best-effort under
# true concurrency.

from __future__ import annotations

import math

__all__ = ["Sketch", "DEFAULT_ALPHA", "DEFAULT_EXEMPLAR_K",
           "merge_sketches"]

DEFAULT_ALPHA = 0.01          # 1% relative error per quantile
DEFAULT_EXEMPLAR_K = 4        # worst exemplars retained per sketch
DEFAULT_MAX_BINS = 2048       # bucket-map bound before collapsing
_MIN_TRACKABLE = 1e-9         # values at/below this land in the zero bin


class Sketch:
    """DDSketch-style relative-error quantile sketch with exemplars.

    Registry-compatible (name/labels like Counter/Gauge/Histogram so
    MetricsRegistry can own instances), but also usable bare — the
    series store builds throwaway delta sketches from snapshot pairs.
    """

    __slots__ = ("name", "labels", "alpha", "gamma", "_log_gamma",
                 "max_bins", "exemplar_k", "bins", "zero", "count",
                 "sum", "exemplars")

    def __init__(self, name: str = "", labels: dict | None = None,
                 alpha: float = DEFAULT_ALPHA,
                 max_bins: int = DEFAULT_MAX_BINS,
                 exemplar_k: int = DEFAULT_EXEMPLAR_K):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"sketch alpha must be in (0, 1), got "
                             f"{alpha}")
        self.name = name
        self.labels = dict(labels or {})
        self.alpha = float(alpha)
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self.gamma)
        self.max_bins = int(max_bins)
        self.exemplar_k = int(exemplar_k)
        self.bins: dict[int, int] = {}
        self.zero = 0                 # observations <= _MIN_TRACKABLE
        self.count = 0
        self.sum = 0.0
        # [value, exemplar_id, seq] — kept sorted is not worth it at
        # k=4; linear min-scan on replacement
        self.exemplars: list = []

    # -- recording (hot path) ------------------------------------------------
    def observe(self, value, exemplar: str | None = None) -> None:
        value = float(value)
        if value <= _MIN_TRACKABLE:
            self.zero += 1
        else:
            index = math.ceil(math.log(value) / self._log_gamma)
            self.bins[index] = self.bins.get(index, 0) + 1
            if len(self.bins) > self.max_bins:
                self._collapse()
        self.count += 1
        self.sum += value
        if exemplar:
            self._note_exemplar(value, str(exemplar))

    def _note_exemplar(self, value: float, exemplar_id: str) -> None:
        entries = self.exemplars
        if len(entries) < self.exemplar_k:
            entries.append([value, exemplar_id, self.count])
            return
        worst_index, worst_value = 0, entries[0][0]
        for i in range(1, len(entries)):
            if entries[i][0] < worst_value:
                worst_index, worst_value = i, entries[i][0]
        if value > worst_value:
            entries[worst_index] = [value, exemplar_id, self.count]

    def _collapse(self) -> None:
        """Fold the lowest buckets together until the map fits the
        bound — only quantiles below the collapsed mass lose accuracy;
        the tail (what SLO rules read) keeps its alpha guarantee."""
        while len(self.bins) > self.max_bins:
            lowest, second = sorted(self.bins)[:2]
            self.bins[second] = self.bins.get(second, 0) + \
                self.bins.pop(lowest)

    def clear(self) -> None:
        """Drop every observation and exemplar (bench warmup boundary;
        production readers take windowed deltas instead)."""
        self.bins.clear()
        self.zero = 0
        self.count = 0
        self.sum = 0.0
        self.exemplars = []

    # -- reading -------------------------------------------------------------
    def quantile(self, q: float):
        """The q-quantile (0..1) within relative error alpha, or None
        on an empty sketch (no evidence ≠ zero latency)."""
        if self.count <= 0:
            return None
        # dict(self.bins) is one C-level (GIL-atomic) copy: unlike
        # Histogram's fixed-length counts list, the bin map GROWS on
        # the lock-free observe() path, and a Python-level iteration
        # racing an insert raises "dictionary changed size" — the
        # registry's best-effort concurrency rule requires reads to
        # tolerate concurrent writers, not crash on them
        bins = dict(self.bins)
        rank = q * (self.count - 1)
        running = self.zero
        if running > rank:
            return 0.0
        for index in sorted(bins):
            running += bins[index]
            if running > rank:
                return 2.0 * self.gamma ** index / (self.gamma + 1.0)
        return 0.0 if not bins else \
            2.0 * self.gamma ** max(bins) / (self.gamma + 1.0)

    @property
    def value(self):
        """Registry-surface compatibility (MetricsRegistry.value)."""
        return self.count

    def worst_exemplars(self, k: int | None = None,
                        min_seq: int = 0) -> list:
        """Top-k exemplars by value, worst first, restricted to those
        observed AFTER the sketch's count was `min_seq` — the windowed
        read: a reader holding the window-start count filters without
        any cross-process clock."""
        entries = [e for e in self.exemplars if e[2] > min_seq]
        entries.sort(key=lambda e: -e[0])
        return entries[:k if k is not None else self.exemplar_k]

    # -- merge ---------------------------------------------------------------
    def merge(self, other: "Sketch") -> "Sketch":
        """Add `other`'s mass into this sketch (in place; returns self).
        Exact: merged bins equal the bins of one sketch fed both
        streams, so quantiles agree to the bit.  Exemplar seqs lose
        their per-source meaning after a merge — merged sketches are
        read-side artifacts, filter windows BEFORE merging."""
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different gamma "
                f"({self.gamma} vs {other.gamma}): re-bucketing would "
                f"break the relative-error guarantee")
        for index, bucket_count in other.bins.items():
            self.bins[index] = self.bins.get(index, 0) + bucket_count
        if len(self.bins) > self.max_bins:
            self._collapse()
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        for value, exemplar_id, _ in other.exemplars:
            self._note_exemplar(value, exemplar_id)
        return self

    # -- wire form -----------------------------------------------------------
    def to_dict(self) -> dict:
        """JSON-able snapshot payload (bin keys become strings — JSON
        has no int keys; from_dict restores them).  The bin map and
        exemplar list are captured with GIL-atomic copies first so a
        concurrent lock-free observe() cannot blow up a registry
        snapshot mid-iteration (see quantile)."""
        bins = dict(self.bins)
        exemplars = list(self.exemplars)
        return {
            "alpha": self.alpha,
            "bins": {str(k): v for k, v in bins.items()},
            "zero": self.zero,
            "count": self.count,
            "sum": self.sum,
            "exemplars": [list(e) for e in exemplars],
        }

    @classmethod
    def from_dict(cls, payload: dict, name: str = "",
                  labels: dict | None = None) -> "Sketch | None":
        """Inverse of to_dict; tolerant of malformed input (a bad
        retained snapshot must never fail a subscriber)."""
        try:
            sketch = cls(name, labels,
                         alpha=float(payload.get("alpha",
                                                 DEFAULT_ALPHA)))
            sketch.bins = {int(k): int(v)
                           for k, v in (payload.get("bins") or
                                        {}).items()}
            sketch.zero = int(payload.get("zero", 0))
            sketch.count = int(payload.get("count", 0))
            sketch.sum = float(payload.get("sum", 0.0))
            sketch.exemplars = [
                [float(e[0]), str(e[1]), int(e[2])]
                for e in (payload.get("exemplars") or [])
                if isinstance(e, (list, tuple)) and len(e) >= 3]
            return sketch
        except (TypeError, ValueError, AttributeError):
            return None


def merge_sketches(sketches) -> Sketch | None:
    """Merge an iterable of sketches into a fresh one (None when the
    iterable is empty) — the fleet-read helper: per-source windowed
    delta sketches in, one fleet-true quantile surface out.

    Sketches whose gamma differs from the first one's are SKIPPED, not
    raised on: the inputs come from network-received snapshots, and a
    foreign/cross-version publisher shipping a different alpha must
    not wedge every Autoscaler.evaluate tick or SLO-rule evaluation
    (the same robustness rule as SeriesStore's stale-kind ring
    replacement)."""
    merged = None
    for sketch in sketches:
        if sketch is None:
            continue
        if merged is None:
            merged = Sketch(sketch.name, sketch.labels,
                            alpha=sketch.alpha,
                            exemplar_k=max(DEFAULT_EXEMPLAR_K,
                                           sketch.exemplar_k))
            merged.merge(sketch)
        elif abs(sketch.gamma - merged.gamma) <= 1e-12:
            merged.merge(sketch)
        # else: incompatible alpha from a foreign publisher — skip
    return merged
