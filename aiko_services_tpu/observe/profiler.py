# Fleet health plane, part 2: the decode-round phase profiler
# (ISSUE 11).
#
# BENCH_r05 measured the decode round at 11.38 ms against a 5.64 ms
# HBM roofline and could only call the difference "overhead".  This
# module ATTRIBUTES it: ContinuousDecoder.pump() marks the boundary of
# every phase of a serving round —
#
#   plan           host-side round planning (active mask, budgets,
#                  cache fit)
#   scan_dispatch  dispatching the compiled decode scan (async)
#   spec_verify    same boundary in speculative mode (the dispatched
#                  program is the widened verify step)
#   admit_dispatch bucketed prefill admits queued behind the scan
#   extend_dispatch chunked-prefill extends queued behind the scan
#   host_sync      the device_get wall — where the device actually
#                  executes everything dispatched above (THIS is the
#                  phase the HBM-bytes model explains)
#   wave_resolve   resolving earlier rounds' deferred admit firsts
#   deliver        walking emissions into callbacks / retirements
#   other          whatever the marks did not cover (bookkeeping,
#                  EWMA) — 1 - other/wall is the attribution fraction
#                  the bench reports
#
# and a PhaseProfiler accumulates wall time per phase.  The mark API
# costs one perf_counter read per boundary (~9 per round against
# millisecond-scale rounds), so it is ALWAYS ON — the bench's
# lat_llama_phase_* fields and the serving_phase_seconds_total registry
# family read the same accumulators.
#
# The HBM-bytes model rides the same phases: the decoder feeds each
# round's modeled device bytes (weights + sized KV read for the scan,
# prefill writes for admits/extends) into the phase that explains
# them, so phase_stats() can report an implied GB/s per phase and the
# roofline gap decomposes into "device streaming at X% of spec
# bandwidth" vs "host-side dispatch/walk time" instead of one opaque
# number.
#
# Opt-in deep capture: arm_trace() opens a jax.profiler trace window
# (XLA-level timeline) for `duration` seconds, armed by environment
# (AIKO_PROFILE_TRACE=<logdir>, AIKO_PROFILE_TRACE_S=<seconds>) or
# programmatically — the HealthAggregator's on_alert hook can arm it,
# so an SLO breach captures the device timeline of the very next
# rounds.  jax imports lazily: observe/ stays importable without
# touching the accelerator stack.

from __future__ import annotations

import os
import time

from .metrics import MetricsRegistry, default_registry

__all__ = ["PhaseProfiler", "PHASES", "arm_trace", "trace_state"]

PHASES = ("plan", "scan_dispatch", "spec_verify", "admit_dispatch",
          "extend_dispatch", "host_sync", "wave_resolve", "deliver",
          "other")

# -- jax.profiler capture window ---------------------------------------------

_trace = {"armed": False, "active": False, "logdir": None,
          "until": 0.0, "duration": 3.0, "captures": 0, "error": None}


def arm_trace(logdir: str, duration: float = 3.0) -> None:
    """Arm a one-shot jax.profiler capture window: the next profiled
    round starts the trace, and it stops `duration` seconds later."""
    _trace["armed"] = True
    _trace["logdir"] = str(logdir)
    _trace["duration"] = float(duration)


def trace_state() -> dict:
    return dict(_trace)


def _env_arm() -> None:
    logdir = os.environ.get("AIKO_PROFILE_TRACE", "")
    if logdir:
        arm_trace(logdir,
                  float(os.environ.get("AIKO_PROFILE_TRACE_S", "3.0")))


_env_arm()


def _trace_tick() -> None:
    """Advance the capture window state machine (called once per
    committed round — zero cost when nothing is armed)."""
    if not (_trace["armed"] or _trace["active"]):
        return
    now = time.perf_counter()
    if _trace["armed"] and not _trace["active"]:
        _trace["armed"] = False
        try:
            import jax
            jax.profiler.start_trace(_trace["logdir"])
            _trace["active"] = True
            _trace["until"] = now + _trace["duration"]
        except Exception as exc:    # profiler unavailable: disarm, note
            _trace["error"] = repr(exc)
        return
    if _trace["active"] and now >= _trace["until"]:
        try:
            import jax
            jax.profiler.stop_trace()
            _trace["captures"] += 1
        except Exception as exc:
            _trace["error"] = repr(exc)
        _trace["active"] = False


class PhaseProfiler:
    """Per-round wall-time attribution into named phases.

    Usage (the pump loop's shape):

        profiler.begin_round()
        ...planning...          ; profiler.mark("plan")
        ...dispatch scan...     ; profiler.mark("scan_dispatch")
        ...
        profiler.commit_round()    # or abandon_round() for idle ticks

    mark(name) charges the time since the previous boundary to `name`;
    commit folds the staged marks into the accumulators and charges
    the unmarked remainder to "other".  abandon_round() discards the
    staged marks — idle pump ticks must not dilute the attribution the
    bench asserts on."""

    def __init__(self, name: str = "decoder",
                 registry: MetricsRegistry | None = None):
        self.name = name
        self.rounds = 0
        self.wall_s = 0.0
        self.phase_s = {phase: 0.0 for phase in PHASES}
        self.phase_bytes = {phase: 0 for phase in PHASES}
        self._t0 = 0.0
        self._last = 0.0
        self._staged: list = []
        self._staged_bytes: dict = {}
        registry = registry or default_registry()
        labels = {"decoder": name}
        self._seconds_counters = {
            phase: registry.counter(
                "serving_phase_seconds_total",
                "decode-round wall seconds by phase",
                labels={**labels, "phase": phase})
            for phase in PHASES}
        self._bytes_counters = {
            phase: registry.counter(
                "serving_phase_bytes_total",
                "modeled device HBM bytes by phase",
                labels={**labels, "phase": phase})
            for phase in PHASES}

    # -- the hot-path mark API (one perf_counter read each) ----------------
    def begin_round(self) -> None:
        self._t0 = self._last = time.perf_counter()
        self._staged = []
        self._staged_bytes = {}

    def mark(self, phase: str) -> None:
        now = time.perf_counter()
        self._staged.append((phase, now - self._last))
        self._last = now

    def add_bytes(self, phase: str, nbytes: int) -> None:
        self._staged_bytes[phase] = \
            self._staged_bytes.get(phase, 0) + int(nbytes)

    def abandon_round(self) -> None:
        self._staged = []
        self._staged_bytes = {}
        # idle ticks still advance the capture window: a trace armed
        # by an alert must STOP on schedule even if decode work
        # ceases right after the breach (load shed/collapsed) —
        # otherwise the capture buffers unboundedly and the artifact
        # never finalizes
        _trace_tick()

    def commit_round(self) -> None:
        total = time.perf_counter() - self._t0
        marked = 0.0
        for phase, dt in self._staged:
            self.phase_s[phase] = self.phase_s.get(phase, 0.0) + dt
            counter = self._seconds_counters.get(phase)
            if counter is not None:
                counter.inc(dt)
            marked += dt
        other = max(0.0, total - marked)
        self.phase_s["other"] += other
        self._seconds_counters["other"].inc(other)
        for phase, nbytes in self._staged_bytes.items():
            self.phase_bytes[phase] = \
                self.phase_bytes.get(phase, 0) + nbytes
            counter = self._bytes_counters.get(phase)
            if counter is not None:
                counter.inc(nbytes)
        self.rounds += 1
        self.wall_s += total
        self._staged = []
        self._staged_bytes = {}
        _trace_tick()

    # -- reporting ----------------------------------------------------------
    def reset(self) -> None:
        self.rounds = 0
        self.wall_s = 0.0
        for phase in self.phase_s:
            self.phase_s[phase] = 0.0
        for phase in self.phase_bytes:
            self.phase_bytes[phase] = 0

    def attributed_fraction(self) -> float:
        """Fraction of committed round wall time carrying a NAMED
        phase (1 - other/wall) — the bench acceptance number."""
        if self.wall_s <= 0:
            return 0.0
        return max(0.0, 1.0 - self.phase_s["other"] / self.wall_s)

    def phase_stats(self) -> dict:
        """{"rounds", "wall_s", "attributed_frac", "phases": {name:
        {"s", "frac", "ms_per_round", "bytes", "gb_per_s"?}}} — phases
        with no time AND no bytes are omitted (speculative vs plain
        mode each uses its own dispatch phase)."""
        phases = {}
        for phase in PHASES:
            seconds = self.phase_s[phase]
            nbytes = self.phase_bytes[phase]
            if seconds <= 0.0 and nbytes <= 0:
                continue
            entry = {
                "s": seconds,
                "frac": seconds / self.wall_s if self.wall_s > 0
                else 0.0,
                "ms_per_round": seconds * 1000.0 / self.rounds
                if self.rounds else 0.0,
                "bytes": nbytes,
            }
            if nbytes and seconds > 0:
                entry["gb_per_s"] = nbytes / seconds / 1e9
            phases[phase] = entry
        return {"rounds": self.rounds, "wall_s": self.wall_s,
                "attributed_frac": self.attributed_fraction(),
                "phases": phases}
