# Distributed tracing: trace contexts with deadlines, and a span
# collector.
#
# The reference framework has "no span/trace IDs" (SURVEY.md §5.1) and
# the local trace.py collector never crossed a process boundary.  This
# module defines the context that DOES cross:
#
#   TraceContext(trace_id, span_id, parent_id, deadline)
#
# carried per remote hop in the binary wire envelope header
# (transport/wire.py; sexpr marker fallback for text transports).  The
# deadline is the frame's END-TO-END budget: every hop the frame takes
# inherits it, the retry machinery clamps backoff to what remains, and
# a hop with no budget left fails fast instead of retrying past the SLO.
#
# Clock domains: a deadline is absolute in the LOCAL engine clock.  On
# the wire it travels as *remaining seconds* plus the sender's send
# timestamp.  When the receiver's clock is COMPARABLE to the sender's —
# the same engine (every deterministic test, the soak, the bench) or
# the same host's monotonic clock, detected by the elapsed time being
# plausible (0 <= now - sent <= CLOCK_COMPARABLE_HORIZON) — wire
# transit and queue dwell are charged to the budget, so a request that
# sat out its SLO in a mailbox arrives already expired.  Across
# machines (monotonic clocks offset by boot times, far outside the
# horizon) the deadline re-anchors without charging transit — no
# wall-clock sync is assumed, the budget just degrades to per-hop.
#
# The Tracer is a process-wide bounded span buffer, OFF by default
# (enable with AIKO_TRACE=1 or tracer.enable()): recording when
# disabled is one attribute check.  Spans are Chrome-trace-shaped
# (name, ts, dur, ids, args) — observe/export.py dumps them as a
# Perfetto-loadable trace-event file.

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

__all__ = [
    "TRACE_MARKER", "TraceContext", "new_trace", "new_span_id",
    "current_trace", "activate", "Tracer", "tracer", "SpanRecord",
]

# transport/wire.py imports this as its header marker (this module has
# no transport dependency, so the import cannot cycle).
TRACE_MARKER = "__aikt__"


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


_new_id = new_span_id

# Largest believable transit+queue time: an elapsed (receiver_now -
# sender_sent) inside this window means the two clocks are comparable
# (same engine, or same-host CLOCK_MONOTONIC); offsets between
# unrelated monotonic clocks are boot-time-sized, far outside it.
CLOCK_COMPARABLE_HORIZON = 3600.0


class TraceContext:
    """One position in a distributed trace, plus the frame's deadline."""
    __slots__ = ("trace_id", "span_id", "parent_id", "deadline", "sent")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str | None = None,
                 deadline: float | None = None,
                 sent: float | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.deadline = deadline
        self.sent = sent            # sender clock at serialization

    def __repr__(self):
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"{' deadline' if self.deadline is not None else ''})")

    def child(self) -> "TraceContext":
        """A child context for one hop: new span id, same trace and
        deadline — the end-to-end budget is inherited, never reset."""
        return TraceContext(self.trace_id, _new_id(),
                            parent_id=self.span_id,
                            deadline=self.deadline)

    def remaining(self, now: float) -> float | None:
        """Budget left at `now` (local engine clock); None = no SLO."""
        return None if self.deadline is None else self.deadline - now

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline

    # -- wire form ---------------------------------------------------------
    def to_fields(self, now: float) -> list:
        """Serializable field list (all strings — sexpr/envelope safe).
        The deadline crosses as remaining-seconds (see module doc)."""
        remaining = "" if self.deadline is None \
            else repr(self.deadline - now)
        return [TRACE_MARKER, self.trace_id, self.span_id,
                remaining, repr(now)]

    @classmethod
    def from_fields(cls, fields, now: float) -> "TraceContext | None":
        """Inverse of to_fields; tolerant of malformed input (a trace
        header must never fail a data-plane message)."""
        if not isinstance(fields, (list, tuple)) or len(fields) < 3 \
                or fields[0] != TRACE_MARKER:
            return None
        trace_id, span_id = str(fields[1]), str(fields[2])
        deadline = sent = None
        try:
            if len(fields) > 4 and fields[4] not in ("", None):
                sent = float(fields[4])
            if len(fields) > 3 and fields[3] not in ("", None):
                remaining = float(fields[3])
                if sent is not None:
                    elapsed = now - sent
                    if 0.0 <= elapsed <= CLOCK_COMPARABLE_HORIZON:
                        # comparable clocks: transit + queue dwell are
                        # part of the end-to-end budget (module doc)
                        remaining -= elapsed
                deadline = now + remaining
        except (TypeError, ValueError):
            deadline = sent = None
        return cls(trace_id, span_id, deadline=deadline, sent=sent)


def new_trace(deadline: float | None = None) -> TraceContext:
    """A fresh root context (new trace id)."""
    return TraceContext(_new_id(), _new_id(), deadline=deadline)


# -- ambient context ---------------------------------------------------------
# Thread-local, not a contextvar: the event engine dispatches handlers
# synchronously per thread, and transport threads must not inherit an
# unrelated caller's context.

_ambient = threading.local()


def current_trace() -> TraceContext | None:
    return getattr(_ambient, "context", None)


@contextmanager
def activate(context: TraceContext | None):
    """Make `context` the ambient trace for the duration (None = no-op
    passthrough, so call sites need no branching)."""
    previous = getattr(_ambient, "context", None)
    _ambient.context = context if context is not None else previous
    try:
        yield context
    finally:
        _ambient.context = previous


# -- span collection ----------------------------------------------------------

@dataclass
class SpanRecord:
    """One finished span, Chrome-trace shaped (ts/dur in SECONDS here;
    the exporter converts to microseconds)."""
    name: str
    ts: float
    dur: float
    trace_id: str = ""
    span_id: str = ""
    parent_id: str = ""
    cat: str = ""
    proc: str = ""
    args: dict = field(default_factory=dict)


class Tracer:
    """Process-wide bounded span buffer + per-name aggregates."""

    def __init__(self, maxlen: int = 65536, enabled: bool = False):
        self.enabled = enabled
        self.spans: deque = deque(maxlen=maxlen)
        self._stats: dict[str, list] = {}   # name -> [count, total_s]
        # span taps: callables invoked with each finished SpanRecord
        # (the flight recorder's intake); fault isolation per tap —
        # a broken tap must not take tracing down with it
        self.taps: list = []

    def enable(self, maxlen: int | None = None) -> None:
        if maxlen is not None and maxlen != self.spans.maxlen:
            self.spans = deque(self.spans, maxlen=maxlen)
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self.spans.clear()
        self._stats.clear()

    def record(self, name: str, ts: float, dur: float,
               context: TraceContext | None = None, cat: str = "",
               proc: str = "", args: dict | None = None,
               span_id: str | None = None,
               parent_id: str | None = None) -> None:
        """Record one finished span.  With `context`, ids default to the
        context's OWN ids (the span IS that context's hop); pass span_id
        to mint a child of the context instead."""
        if not self.enabled:
            return
        if context is not None:
            trace_id = context.trace_id
            if span_id is None:
                span_id = context.span_id
                parent_id = parent_id or context.parent_id or ""
            else:
                parent_id = parent_id or context.span_id
        else:
            trace_id = ""
        span = SpanRecord(
            name=name, ts=ts, dur=dur, trace_id=trace_id,
            span_id=span_id or "", parent_id=parent_id or "",
            cat=cat, proc=proc, args=dict(args or {}))
        self.spans.append(span)
        for tap in self.taps:
            try:
                tap(span)
            except Exception:       # a broken tap must not kill tracing
                pass
        entry = self._stats.get(name)
        if entry is None:
            entry = self._stats[name] = [0, 0.0]
        entry[0] += 1
        entry[1] += dur

    @contextmanager
    def span(self, name: str, context: TraceContext | None = None,
             cat: str = "", proc: str = "", args: dict | None = None):
        """Time a synchronous section; records on exit (child span of
        `context` when given).  Cheap no-op when disabled."""
        if not self.enabled:
            yield None
            return
        start = time.perf_counter()
        try:
            yield None
        finally:
            self.record(name, start, time.perf_counter() - start,
                        context=context, cat=cat, proc=proc, args=args,
                        span_id=_new_id() if context is not None
                        else None)

    def stats(self) -> dict:
        """Per-span-name aggregates: {name: {count, total_s, mean_s}} —
        the per-hop span stats the chaos soak report embeds."""
        return {name: {"count": count, "total_s": total,
                       "mean_s": total / count if count else 0.0}
                for name, (count, total) in sorted(self._stats.items())}


tracer = Tracer(enabled=os.environ.get("AIKO_TRACE", "").lower() not in
                ("", "0", "false", "no", "off"))
