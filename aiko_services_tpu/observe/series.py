# Fleet health plane, part 1: an in-process time-series store over the
# retained metrics snapshots, declarative SLO rules with multi-window
# burn-rate alerting, and the HealthAggregator that evaluates them
# fleet-wide (ISSUE 11).
#
# Everything the runtime measured so far was POINT-IN-TIME: the
# autoscaler acted on the single latest retained snapshot, nobody kept
# history, and when a chaos soak breached an SLO the evidence was
# already gone.  This module is the layer that records and alerts on
# reality continuously, in the style of Monarch's in-memory time
# series:
#
#   * SeriesStore — bounded ring-buffer history per (source, series):
#     counters and gauges as (t, value) samples, histograms as
#     (t, bucket-counts) samples so WINDOWED quantiles come from
#     bucket-count DELTAS — a cumulative histogram polluted by an
#     earlier scenario cannot leak into this window's percentile;
#   * SLORule — declarative rules over series selectors
#     ("family{label=value}:p95"): `ratio` rules burn an error budget
#     (bad / (bad + good) event deltas) and fire on the SRE-workbook
#     multi-window discipline — a (long, short, threshold) pair fires
#     only when BOTH windows burn, so a transient blip (short only) and
#     stale history (long only) both stay quiet; `level` rules watch a
#     windowed worst value (gauge level or histogram delta-quantile)
#     with a persistence requirement (`for_seconds`);
#   * HealthAggregator — subscribes the retained {topic}/0/metrics
#     snapshots fleet-wide (the same intake the Autoscaler and the
#     Dashboard use), appends every family into the store, evaluates
#     the rules each tick, and publishes RETAINED alert records on
#     {namespace}/alert/{rule} that the Dashboard, the Recorder, and
#     the flight-recorder dump trigger consume.
#
# Near-leaf like the rest of observe/: the aggregator is duck-typed on
# the ProcessRuntime surface (add_message_handler / publish / event),
# NOT an Actor — importing actor.py here would cycle the import graph
# (actor records wire spans into this package).

from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass

from .export import METRICS_TOPIC_SUFFIX, parse_retained_json
from .metrics import MetricsRegistry, default_registry
from .sketch import Sketch, merge_sketches
from ..utils import get_logger

__all__ = [
    "ScalarSeries", "HistogramSeries", "SketchSeries", "SeriesStore",
    "SLORule", "HealthAggregator", "parse_selector",
    "ALERT_TOPIC_PREFIX",
]

ALERT_TOPIC_PREFIX = "alert"

# samples kept per series ring: at the MetricsPublisher's default 5 s
# interval this covers ~5 minutes of history; tighter intervals shorten
# the window rather than growing memory (the store is bounded by
# construction, like every other ring in the runtime)
DEFAULT_RING_SAMPLES = 64


def parse_selector(text: str):
    """Parse a series selector "family{label=value,...}:pNN" into
    (family, labels dict, quantile or None).  Labels are a SUBSET
    match; the quantile suffix selects a histogram percentile (p95 →
    0.95).  The grammar is deliberately tiny — it has to be writable in
    a soak script and readable in an alert record."""
    text = text.strip()
    quantile = None
    base, sep, suffix = text.rpartition(":")
    if sep and suffix.startswith("p"):
        try:
            quantile = float(suffix[1:]) / 100.0
            text = base
        except ValueError:
            quantile = None
    labels: dict = {}
    if text.endswith("}") and "{" in text:
        text, _, inner = text.partition("{")
        for pair in inner[:-1].split(","):
            if not pair.strip():
                continue
            key, _, value = pair.partition("=")
            labels[key.strip()] = value.strip()
    return text, labels, quantile


class ScalarSeries:
    """Bounded ring of (t, value) samples for one counter/gauge series."""
    __slots__ = ("name", "labels", "kind", "points")

    def __init__(self, name: str, labels: dict, kind: str,
                 maxlen: int = DEFAULT_RING_SAMPLES):
        self.name = name
        self.labels = dict(labels)
        self.kind = kind                       # "counter" | "gauge"
        self.points: deque = deque(maxlen=maxlen)

    def append(self, t: float, value: float) -> None:
        self.points.append((float(t), float(value)))

    def _window(self, now: float, window: float) -> list:
        cutoff = now - window
        return [(t, v) for t, v in self.points if t >= cutoff]

    def latest(self, now: float, window: float):
        """Most recent value within the window, or None — the
        freshness-bounded LEVEL read (replaces the autoscaler's ad-hoc
        snapshot-horizon staleness pruning)."""
        points = self._window(now, window)
        return points[-1][1] if points else None

    def maximum(self, now: float, window: float):
        points = self._window(now, window)
        return max(v for _, v in points) if points else None

    def delta(self, now: float, window: float) -> float:
        """newest - oldest value inside the window; 0.0 with fewer than
        two samples.  A single sample is a BASELINE, not a delta — this
        is what keeps cumulative counters from an earlier scenario (the
        registry is process-wide) out of this window's rate."""
        points = self._window(now, window)
        if len(points) < 2:
            return 0.0
        return points[-1][1] - points[0][1]

    def trend(self, now: float, window: float):
        """Slope in value/second over the window (None with <2 samples
        or zero time spread) — the leading-edge signal a level
        threshold only sees after the fact."""
        points = self._window(now, window)
        if len(points) < 2:
            return None
        dt = points[-1][0] - points[0][0]
        if dt <= 0:
            return None
        return (points[-1][1] - points[0][1]) / dt


class HistogramSeries:
    """Bounded ring of (t, cumulative bucket counts) samples for one
    histogram series — windowed quantiles come from count DELTAS."""
    __slots__ = ("name", "labels", "bounds", "points")

    def __init__(self, name: str, labels: dict, bounds,
                 maxlen: int = DEFAULT_RING_SAMPLES):
        self.name = name
        self.labels = dict(labels)
        self.bounds = tuple(float(b) for b in bounds)
        self.points: deque = deque(maxlen=maxlen)

    def append(self, t: float, counts) -> None:
        self.points.append((float(t), tuple(int(c) for c in counts)))

    def _window(self, now: float, window: float) -> list:
        cutoff = now - window
        return [(t, c) for t, c in self.points if t >= cutoff]

    def delta_counts(self, now: float, window: float,
                     baseline_empty: bool = False):
        """Bucket-count deltas across the window (newest - oldest).
        With one sample: None normally (a baseline is not a delta), or
        the sample itself when `baseline_empty` — the first sight of a
        process counts everything it reports (the Autoscaler's
        compatibility mode; rule evaluation never uses it)."""
        points = self._window(now, window)
        if not points:
            return None
        if len(points) < 2:
            return points[-1][1] if baseline_empty else None
        oldest, newest = points[0][1], points[-1][1]
        if len(oldest) != len(newest):
            return newest if baseline_empty else None
        return tuple(max(0, n - o) for n, o in zip(newest, oldest))

    def delta_quantile(self, q: float, now: float, window: float,
                       baseline_empty: bool = False):
        """Approximate windowed quantile (upper bound of the bucket
        holding the q-th windowed observation), or None when the window
        holds no evidence — same diagnostic grade as
        Histogram.quantile, minus the cumulative contamination."""
        counts = self.delta_counts(now, window, baseline_empty)
        if not counts or not self.bounds:
            return None
        total = sum(counts)
        if not total:
            return None
        target = q * total
        running = 0
        for index, bucket_count in enumerate(counts):
            running += bucket_count
            if running >= target:
                return self.bounds[min(index, len(self.bounds) - 1)]
        return self.bounds[-1]

    def delta_count(self, now: float, window: float) -> int:
        counts = self.delta_counts(now, window)
        return sum(counts) if counts else 0


class SketchSeries:
    """Bounded ring of (t, sketch payload dict) samples for one
    mergeable quantile sketch series (observe/sketch.py).  The payload
    is the cumulative to_dict() form straight off the snapshot;
    windowed reads reconstruct a DELTA sketch from the newest/oldest
    pair (bin-count subtraction — same anti-contamination discipline
    as HistogramSeries), and the store merges delta sketches ACROSS
    SOURCES so a level rule reads one fleet-true quantile instead of
    worst-of-per-process (ISSUE 12)."""
    __slots__ = ("name", "labels", "points")

    def __init__(self, name: str, labels: dict,
                 maxlen: int = DEFAULT_RING_SAMPLES):
        self.name = name
        self.labels = dict(labels)
        self.points: deque = deque(maxlen=maxlen)

    def append(self, t: float, payload: dict) -> None:
        self.points.append((float(t), dict(payload)))

    def _window(self, now: float, window: float) -> list:
        cutoff = now - window
        return [(t, p) for t, p in self.points if t >= cutoff]

    def delta_sketch(self, now: float, window: float,
                     baseline_empty: bool = False) -> Sketch | None:
        """The window's worth of observations as a fresh Sketch, or
        None without two samples (a baseline is not a delta; the same
        rule ScalarSeries.delta applies).  Exemplars keep only entries
        whose seq postdates the window-start count — clock-free window
        filtering (sketch.py module doc)."""
        points = self._window(now, window)
        if not points:
            return None
        if len(points) < 2:
            return Sketch.from_dict(points[-1][1], self.name,
                                    self.labels) \
                if baseline_empty else None
        newest = Sketch.from_dict(points[-1][1], self.name, self.labels)
        oldest = Sketch.from_dict(points[0][1])
        if newest is None:
            return None
        if oldest is None or abs(oldest.gamma - newest.gamma) > 1e-12:
            return newest if baseline_empty else None
        delta = Sketch(self.name, self.labels, alpha=newest.alpha,
                       exemplar_k=max(newest.exemplar_k,
                                      len(newest.exemplars) or 1))
        delta.bins = {
            index: count - oldest.bins.get(index, 0)
            for index, count in newest.bins.items()
            if count - oldest.bins.get(index, 0) > 0}
        delta.zero = max(0, newest.zero - oldest.zero)
        delta.count = delta.zero + sum(delta.bins.values())
        delta.sum = max(0.0, newest.sum - oldest.sum)
        delta.exemplars = [list(e) for e in newest.exemplars
                           if e[2] > oldest.count]
        return delta


class SeriesStore:
    """Per-(source, series) history over registry snapshots.

    `source` is the publishing process's topic_path; series identity is
    (family name, label items) exactly as the registry keys them.  The
    store is bounded twice: per-ring sample count and total series
    count (beyond `max_series`, new series are dropped with a counter —
    an unbounded-label bug upstream must not OOM the aggregator; the
    lint-metric-label graft-check rule polices the source)."""

    def __init__(self, window: float = 300.0,
                 ring_samples: int = DEFAULT_RING_SAMPLES,
                 max_series: int = 4096,
                 registry: MetricsRegistry | None = None):
        self.window = float(window)
        self.ring_samples = int(ring_samples)
        self.max_series = int(max_series)
        self._series: dict[tuple, object] = {}
        self._newest: dict[str, float] = {}     # source -> last append t
        registry = registry or default_registry()
        self._dropped = registry.counter(
            "health_series_dropped_total",
            "series refused by the store's max_series bound")

    def __len__(self) -> int:
        return len(self._series)

    @staticmethod
    def _key(source: str, name: str, labels: dict) -> tuple:
        return (source, name, tuple(sorted(labels.items())))

    def _get(self, source, name, labels, factory, ring_class):
        key = self._key(source, name, labels)
        ring = self._series.get(key)
        if ring is not None and not isinstance(ring, ring_class):
            # the source re-shipped this family under the OTHER metric
            # type (publisher upgrade reusing a retained topic_path):
            # the old history is meaningless for the new kind — replace
            # the ring instead of crashing every later snapshot's
            # intake with a type error
            del self._series[key]
            ring = None
        if ring is None:
            if len(self._series) >= self.max_series:
                self._dropped.inc()
                return None
            ring = self._series[key] = factory()
        return ring

    def append_scalar(self, source: str, name: str, labels: dict,
                      t: float, value, kind: str = "gauge",
                      seed_zero_t: float | None = None) -> None:
        key = self._key(source, name, labels)
        new_series = key not in self._series
        ring = self._get(source, name, labels,
                         lambda: ScalarSeries(name, labels, kind,
                                              self.ring_samples),
                         ScalarSeries)
        if ring is not None:
            if new_series and seed_zero_t is not None \
                    and kind != "gauge":
                # series BORN mid-flight from an already-known source
                # (registry counters create lazily on first increment):
                # it was provably zero the last time this source
                # reported, so seed that — without it the birth burst
                # reads as a baseline and the whole first window of
                # events vanishes from every rate
                ring.append(seed_zero_t, 0.0)
            ring.append(t, value)
            self._newest[source] = t

    def append_histogram(self, source: str, name: str, labels: dict,
                         t: float, bounds, counts,
                         seed_zero_t: float | None = None) -> None:
        key = self._key(source, name, labels)
        new_series = key not in self._series
        ring = self._get(source, name, labels,
                         lambda: HistogramSeries(name, labels, bounds,
                                                 self.ring_samples),
                         HistogramSeries)
        if ring is not None:
            if new_series and seed_zero_t is not None:
                ring.append(seed_zero_t, (0,) * len(counts))
            ring.append(t, counts)
            self._newest[source] = t

    def append_sketch(self, source: str, name: str, labels: dict,
                      t: float, payload: dict,
                      seed_zero_t: float | None = None) -> None:
        key = self._key(source, name, labels)
        new_series = key not in self._series
        ring = self._get(source, name, labels,
                         lambda: SketchSeries(name, labels,
                                              self.ring_samples),
                         SketchSeries)
        if ring is not None:
            if new_series and seed_zero_t is not None:
                # empty cumulative payload at the previous snapshot
                # time — the same birth-seeding rule as counters: a
                # sketch born mid-flight from a known source counts its
                # first burst as the delta it is
                ring.append(seed_zero_t,
                            {"alpha": payload.get("alpha"), "bins": {},
                             "zero": 0, "count": 0, "sum": 0.0,
                             "exemplars": []})
            ring.append(t, payload)
            self._newest[source] = t

    def append_snapshot(self, source: str, snapshot: dict, t: float,
                        families=None) -> int:
        """Append every series of one MetricsRegistry.snapshot()
        document (optionally filtered to `families`); returns series
        appended.  This is the ONE schema bridge between the publisher
        and the store — the round-trip test pins it."""
        appended = 0
        # birth seeding: captured ONCE before any append mutates
        # _newest — a source's FIRST-EVER snapshot must stay a pure
        # baseline (its cumulative values may predate this store), but
        # a series appearing in a LATER snapshot was zero at the
        # previous one
        seed_zero_t = self._newest.get(source)
        for name, entry in snapshot.items():
            if families is not None and name not in families:
                continue
            kind = entry.get("type", "gauge")
            for series in entry.get("series", []):
                labels = series.get("labels", {}) or {}
                if kind == "histogram":
                    bounds = series.get("bounds") or []
                    counts = series.get("counts") or []
                    if bounds and counts:
                        self.append_histogram(source, name, labels, t,
                                              bounds, counts,
                                              seed_zero_t=seed_zero_t)
                        appended += 1
                elif kind == "sketch":
                    if "bins" in series:
                        self.append_sketch(source, name, labels, t,
                                           series,
                                           seed_zero_t=seed_zero_t)
                        appended += 1
                elif "value" in series:
                    self.append_scalar(source, name, labels, t,
                                       series["value"], kind,
                                       seed_zero_t=seed_zero_t)
                    appended += 1
        return appended

    def rings(self, name: str, labels: dict | None = None) -> list:
        """Every ring of one family across all sources whose labels
        are a superset of `labels`: [(source, ring), ...]."""
        out = []
        for (source, ring_name, _), ring in self._series.items():
            if ring_name != name:
                continue
            if labels and any(ring.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out.append((source, ring))
        return out

    def sources(self) -> list:
        return sorted(self._newest)

    def prune(self, now: float) -> int:
        """Drop every series of sources silent for > 2x the window —
        dead processes under restart churn each left history behind
        under a unique pid topic_path; the store must not grow without
        bound.  Returns series dropped."""
        horizon = now - 2.0 * self.window
        dead = [s for s, t in self._newest.items() if t < horizon]
        if not dead:
            return 0
        dead_set = set(dead)
        victims = [key for key in self._series if key[0] in dead_set]
        for key in victims:
            del self._series[key]
        for source in dead:
            del self._newest[source]
        return len(victims)

    # -- selector-driven reads (SLO rules) ----------------------------------
    def selector_delta(self, selector: str, now: float,
                       window: float) -> float:
        """Summed windowed event delta across every series matching a
        counter/histogram selector (histograms contribute their
        windowed observation count)."""
        name, labels, _ = parse_selector(selector)
        total = 0.0
        for _, ring in self.rings(name, labels):
            if isinstance(ring, HistogramSeries):
                total += ring.delta_count(now, window)
            elif isinstance(ring, SketchSeries):
                delta = ring.delta_sketch(now, window)
                total += delta.count if delta is not None else 0
            else:
                total += max(0.0, ring.delta(now, window))
        return total

    def sketch_window(self, selector: str, now: float, window: float,
                      baseline_empty: bool = False) -> list:
        """Every matching SketchSeries' windowed delta sketch:
        [(source, Sketch), ...] — the ONE reconstruction pass both the
        merged quantile and the exemplar read derive from (a
        continuously breaching rule must not rebuild every source's
        delta twice per evaluation tick)."""
        name, labels, _ = parse_selector(selector)
        out = []
        for source, ring in self.rings(name, labels):
            if not isinstance(ring, SketchSeries):
                continue
            delta = ring.delta_sketch(now, window, baseline_empty)
            if delta is not None:
                out.append((source, delta))
        return out

    def merged_sketch(self, selector: str, now: float,
                      window: float,
                      baseline_empty: bool = False) -> Sketch | None:
        """ONE windowed sketch merging every matching SketchSeries
        across every source — the fleet-true quantile surface (ISSUE
        12): merged(A, B) equals one-sketch(A ∪ B) by construction, so
        a level rule over this reads the latency distribution the
        FLEET served, not the worst process's.  None when no source
        has windowed evidence."""
        return merge_sketches(
            delta for _, delta in self.sketch_window(
                selector, now, window, baseline_empty))

    def selector_exemplars(self, selector: str, now: float,
                           window: float, k: int = 8,
                           deltas: list | None = None) -> list:
        """Worst-first windowed exemplars across every matching sketch
        series: [{"trace_id", "value", "source"}, ...] — the trace ids
        a firing alert points at (metrics → traces).  Pass `deltas`
        (a sketch_window result) to reuse an already-built pass."""
        if deltas is None:
            deltas = self.sketch_window(selector, now, window)
        entries = []
        for source, delta in deltas:
            for value, exemplar_id, _seq in delta.worst_exemplars(k):
                entries.append({"trace_id": exemplar_id,
                                "value": value, "source": source})
        entries.sort(key=lambda e: -e["value"])
        # one entry per trace id: the same request may be the worst in
        # several windows/series
        seen, unique = set(), []
        for entry in entries:
            if entry["trace_id"] in seen:
                continue
            seen.add(entry["trace_id"])
            unique.append(entry)
        return unique[:k]

    def selector_level(self, selector: str, now: float, window: float,
                       sketch_deltas: list | None = None):
        """Worst (max) windowed value across matching series: histogram
        selectors read the windowed delta-quantile (default p95),
        scalars the windowed maximum, and SKETCH selectors the
        quantile of the cross-source MERGED windowed sketch (fleet-true
        rather than worst-of).  None = no evidence in window.  Pass
        `sketch_deltas` (a sketch_window result) to reuse an
        already-built reconstruction pass."""
        name, labels, quantile = parse_selector(selector)
        worst = None
        sketch_rings = False
        for _, ring in self.rings(name, labels):
            if isinstance(ring, SketchSeries):
                sketch_rings = True
                continue
            if isinstance(ring, HistogramSeries):
                value = ring.delta_quantile(quantile or 0.95, now,
                                            window)
            else:
                value = ring.maximum(now, window)
            if value is not None and (worst is None or value > worst):
                worst = value
        if sketch_rings or sketch_deltas:
            if sketch_deltas is None:
                sketch_deltas = self.sketch_window(selector, now,
                                                   window)
            merged = merge_sketches(d for _, d in sketch_deltas)
            value = merged.quantile(quantile or 0.95) \
                if merged is not None else None
            if value is not None and (worst is None or value > worst):
                worst = value
        return worst


@dataclass(frozen=True)
class SLORule:
    """One declarative SLO rule (grammar documented in README):

    ratio — error-budget burn over event counters:
        error_rate(w) = bad_delta(w) / (bad_delta(w) + good_delta(w))
        burn(w)       = error_rate(w) / (1 - objective)
      breaches when, for ANY (long, short, threshold) pair in `pairs`,
      burn(long) >= threshold AND burn(short) >= threshold — the
      multi-window discipline: the short window proves it is happening
      NOW, the long window proves it is not a blip.

    level — windowed worst value against a threshold:
        value(w) = worst matching series level (histogram selectors
        read the windowed delta-quantile, e.g. ":p95")
      breaches when value(short) >= threshold with the breach sustained
      `for_seconds` (the aggregator tracks persistence)."""
    name: str
    kind: str                      # "ratio" | "level"
    bad: str = ""                  # ratio: bad-events selector
    good: str = ""                 # ratio: good-events selector
    series: str = ""               # level: value selector
    objective: float = 0.999      # ratio: SLO target (good fraction)
    threshold: float = 0.0         # level: breach threshold
    pairs: tuple = ((300.0, 60.0, 2.0),)  # ratio: (long_s, short_s, burn)
    window: float = 60.0           # level: evidence window
    for_seconds: float = 0.0       # level: required persistence
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("ratio", "level"):
            raise ValueError(f"SLORule kind must be ratio|level, got "
                             f"{self.kind!r}")
        if self.kind == "ratio" and not (self.bad and self.good):
            raise ValueError(f"ratio rule {self.name!r} needs bad= and "
                             f"good= selectors")
        if self.kind == "level" and not self.series:
            raise ValueError(f"level rule {self.name!r} needs series=")
        if self.kind == "ratio" and not 0.0 < self.objective < 1.0:
            raise ValueError(f"ratio rule {self.name!r}: objective must "
                             f"be in (0, 1)")

    def evaluate(self, store: SeriesStore, now: float) -> dict:
        """Instantaneous verdict: {"breaching": bool, ...evidence}.
        Persistence (`for_seconds`) and alert state transitions are the
        aggregator's job, not the rule's — rules stay pure functions of
        the store."""
        if self.kind == "ratio":
            budget = 1.0 - self.objective
            burns = []
            breaching = False
            for long_w, short_w, burn_threshold in self.pairs:
                def burn(window):
                    bad = store.selector_delta(self.bad, now, window)
                    good = store.selector_delta(self.good, now, window)
                    total = bad + good
                    rate = bad / total if total > 0 else 0.0
                    return rate / budget if budget > 0 else 0.0
                long_burn, short_burn = burn(long_w), burn(short_w)
                burns.append({"long_s": long_w, "short_s": short_w,
                              "burn_long": round(long_burn, 4),
                              "burn_short": round(short_burn, 4),
                              "threshold": burn_threshold})
                if long_burn >= burn_threshold and \
                        short_burn >= burn_threshold:
                    breaching = True
            return {"breaching": breaching, "kind": "ratio",
                    "objective": self.objective, "windows": burns}
        # ONE delta-sketch reconstruction per tick: the level read and
        # the exemplar read share it
        deltas = store.sketch_window(self.series, now, self.window)
        value = store.selector_level(self.series, now, self.window,
                                     sketch_deltas=deltas)
        verdict = {"breaching": value is not None and
                   value >= self.threshold,
                   "kind": "level", "value": value,
                   "threshold": self.threshold,
                   "window_s": self.window}
        if verdict["breaching"]:
            # sketch-backed selectors carry the worst windowed
            # exemplars — the trace ids BEHIND the breaching quantile
            # (ISSUE 12: alert → journeys closed loop); empty for
            # histogram/scalar series, which retain no identities
            exemplars = store.selector_exemplars(self.series, now,
                                                 self.window,
                                                 deltas=deltas)
            if exemplars:
                verdict["exemplars"] = exemplars
        return verdict


class HealthAggregator:
    """Fleet-wide SLO watchdog over the retained metrics snapshots.

    Subscribes {namespace}/+/+/0/metrics (every MetricsPublisher in the
    namespace), appends each document into a SeriesStore, and evaluates
    the SLO rules every `interval` seconds on the runtime's engine —
    deterministic under a VirtualClock like everything else.  Alert
    records publish RETAINED on {namespace}/alert/{rule}, so a
    late-joining Dashboard or Recorder still sees the current state;
    `on_alert` callbacks fire on the inactive→firing TRANSITION only
    (the flight-recorder dump trigger rides this — every breach ships
    one postmortem, not one per evaluation tick)."""

    def __init__(self, runtime, rules=(), interval: float = 1.0,
                 window: float = 300.0, name: str = "health",
                 store: SeriesStore | None = None,
                 topic_filter: str | None = None,
                 families=None, retain_alerts: bool = True,
                 registry: MetricsRegistry | None = None):
        self.runtime = runtime
        self.name = name
        self.rules = list(rules)
        self.store = store or SeriesStore(window=window)
        self.families = set(families) if families is not None else None
        self.retain_alerts = retain_alerts
        self.logger = get_logger(f"health.{name}")
        self.on_alert: list = []          # callbacks (rule, record)
        self.alerts: dict[str, dict] = {}     # rule name -> last record
        self.fired: dict[str, int] = {}   # rule name -> firing count
        # rule name -> {"breach_since": t|None, "firing": bool}
        self._state: dict[str, dict] = {
            rule.name: {"breach_since": None, "firing": False}
            for rule in self.rules}
        self._filter = topic_filter or \
            f"{runtime.namespace}/+/+/{METRICS_TOPIC_SUFFIX}"
        self._registry = registry or default_registry()
        labels = {"aggregator": name}
        self._snapshots_seen = self._registry.counter(
            "health_snapshots_total",
            "metrics snapshots ingested by the aggregator", labels)
        self._alert_counters: dict = {}
        self._labels = labels
        runtime.add_message_handler(self._metrics_handler, self._filter)
        self._timer = runtime.event.add_timer_handler(self.evaluate,
                                                      float(interval))

    # -- intake -------------------------------------------------------------
    def _metrics_handler(self, topic: str, payload) -> None:
        document = parse_retained_json(payload, require_key="snapshot")
        if document is None:
            self.logger.debug("health %s: unparseable snapshot on %s",
                              self.name, topic)
            return
        source = str(document.get("topic_path", topic))
        # stamped on the RECEIVER's clock: windowed reads compare
        # against this engine's now(), and cross-machine publisher
        # clocks are not assumed comparable (same rule as tracing's
        # deadline re-anchor)
        now = self.runtime.event.clock.now()
        self.store.append_snapshot(source, document["snapshot"], now,
                                   families=self.families)
        self._snapshots_seen.inc()

    # -- evaluation ---------------------------------------------------------
    def _count_alert(self, rule_name: str, state: str) -> None:
        key = (rule_name, state)
        counter = self._alert_counters.get(key)
        if counter is None:
            counter = self._registry.counter(
                "health_alerts_total",
                "SLO alert transitions by rule and state",
                labels={**self._labels, "rule": rule_name,
                        "state": state})
            self._alert_counters[key] = counter
        counter.inc()

    def _publish_alert(self, record: dict) -> None:
        topic = f"{self.runtime.namespace}/{ALERT_TOPIC_PREFIX}/" \
                f"{record['rule']}"
        try:
            self.runtime.publish(topic, json.dumps(record, default=str),
                                 retain=self.retain_alerts)
        except Exception:
            self.logger.exception("health %s: alert publish failed",
                                  self.name)

    def evaluate(self) -> None:
        """One evaluation tick (engine timer): every rule against the
        store, persistence tracking, state transitions, retained alert
        records."""
        now = self.runtime.event.clock.now()
        self.store.prune(now)
        for rule in self.rules:
            # keyed by the aggregator's fixed rule set — bounded:
            # graft: disable=lint-unbounded-cache
            state = self._state.setdefault(
                rule.name, {"breach_since": None, "firing": False})
            try:
                verdict = rule.evaluate(self.store, now)
            except Exception:
                self.logger.exception("health %s: rule %s evaluation "
                                      "failed", self.name, rule.name)
                continue
            if verdict["breaching"]:
                if state["breach_since"] is None:
                    state["breach_since"] = now
                sustained = now - state["breach_since"] >= \
                    rule.for_seconds
                if sustained and not state["firing"]:
                    state["firing"] = True
                    record = {
                        "rule": rule.name, "state": "firing",
                        "since": state["breach_since"], "time": now,
                        "description": rule.description,
                        "detail": verdict,
                        # exemplar trace ids hoisted top-level so every
                        # consumer (Recorder, DumpOnAlert, an operator
                        # reading the retained record) finds them
                        # without knowing the verdict schema
                        "exemplars": [e["trace_id"] for e in
                                      verdict.get("exemplars", [])],
                    }
                    # graft: disable=lint-unbounded-cache
                    self.alerts[rule.name] = record
                    # graft: disable=lint-unbounded-cache (rule set)
                    self.fired[rule.name] = \
                        self.fired.get(rule.name, 0) + 1
                    self._count_alert(rule.name, "firing")
                    self._publish_alert(record)
                    self.logger.warning(
                        "SLO alert FIRING: %s (%s)", rule.name,
                        rule.description or rule.kind)
                    for callback in list(self.on_alert):
                        try:
                            callback(rule, record)
                        except Exception:
                            self.logger.exception(
                                "health %s: on_alert callback failed",
                                self.name)
            else:
                state["breach_since"] = None
                if state["firing"]:
                    state["firing"] = False
                    record = {"rule": rule.name, "state": "resolved",
                              "time": now,
                              "description": rule.description,
                              "detail": verdict}
                    # graft: disable=lint-unbounded-cache
                    self.alerts[rule.name] = record
                    self._count_alert(rule.name, "resolved")
                    self._publish_alert(record)
                    self.logger.warning("SLO alert resolved: %s",
                                        rule.name)

    def firing(self) -> list:
        """Names of rules currently in the firing state."""
        return sorted(name for name, state in self._state.items()
                      if state["firing"])

    def stop(self) -> None:
        if self._timer is not None:
            self.runtime.event.remove_timer_handler(self._timer)
            self._timer = None
        self.runtime.remove_message_handler(self._metrics_handler,
                                            self._filter)
