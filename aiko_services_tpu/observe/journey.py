# Request journeys: per-request lifecycle records for the serving path
# (ISSUE 12).
#
# The fleet health plane (PR 11) watches AGGREGATES; when its alert
# fires, nobody could answer "which requests, and where did THEIR time
# go?".  A RequestJourney is that answer for one ContinuousDecoder
# request:
#
#   * the pipeline ADMISSION verdict and measured fair-queue wait
#     (ops/admission.py — delivered here through a bounded
#     note_admission/take_admission_note handoff keyed by trace id, so
#     ops/ and serving/ stay uncoupled);
#   * decoder QUEUE time (submit → slot assigned) and the prefill
#     admit/extend WAVES the request rode;
#   * a BOUNDED ring of per-token emission timestamps (the request's
#     own inter-token-latency distribution, not the fleet's);
#   * the deadline margin at completion and the outcome
#     (deadline-met / deadline-missed / no-deadline / shed).
#
# Journeys correlate to the frame's existing TraceContext: the decoder
# captures the AMBIENT trace at submit (the serving walk runs under
# the caller's context — pipeline.process_frame_remote activates it),
# so ONE trace id spans wire hop → admission → decoder slot → token
# stream.  On completion the JourneyLog emits the journey as CHILD
# SPANS of that context into the process Tracer —
# journey:request > journey:admission / journey:queue /
# journey:prefill / journey:token — which the flight-recorder taps
# route into the PR 11 rings, so a DumpOnAlert postmortem contains the
# journeys of the alert's exemplar trace ids with zero extra plumbing.
#
# Clock domains, stated honestly: journey timestamps are the decoder's
# scheduler clock (time.monotonic — the same stamps ttft_samples
# already used), while the pipeline admission note's queue wait is
# measured on the ENGINE clock (virtual in tests).  The two are carried
# as separate fields, never subtracted across domains; span ordering
# in a merged flight dump is by trace id, not by cross-domain
# timestamp (observe/flight.py module doc).

from __future__ import annotations

from collections import OrderedDict, deque

from .metrics import MetricsRegistry, default_registry
from .tracing import TraceContext, new_span_id, \
    tracer as _global_tracer

__all__ = ["RequestJourney", "JourneyLog", "note_admission",
           "take_admission_note", "pending_admission_notes",
           "tenant_slo_rows", "DEFAULT_TOKEN_RING"]

DEFAULT_TOKEN_RING = 64       # per-request token timestamps retained
_NOTE_CAP = 512               # pending admission notes (bounded)

# trace_id -> {"verdict", "queue_wait_s", "tenant", "tier"}; insertion
# ordered so the bound sheds OLDEST — a note whose request died before
# reaching a decoder ages out instead of leaking
_pending_notes: OrderedDict[str, dict] = OrderedDict()


def note_admission(trace_id: str, verdict: str,
                   queue_wait_s: float | None = None,
                   tenant: str = "", tier: int = 1) -> None:
    """Record one admission verdict for the journey that MAY follow
    (pipeline.process_frame_remote calls this just before the serving
    walk runs; the decoder's submit — synchronous inside that walk —
    collects it).  Bounded at _NOTE_CAP, oldest shed."""
    if not trace_id:
        return
    _pending_notes[str(trace_id)] = {
        "verdict": str(verdict),
        "queue_wait_s": queue_wait_s,
        "tenant": str(tenant or ""),
        "tier": int(tier),
    }
    _pending_notes.move_to_end(str(trace_id))
    while len(_pending_notes) > _NOTE_CAP:
        _pending_notes.popitem(last=False)


def take_admission_note(trace_id: str) -> dict | None:
    """Claim (and remove) the pending admission note for a trace id."""
    if not trace_id:
        return None
    return _pending_notes.pop(str(trace_id), None)


def pending_admission_notes() -> int:
    return len(_pending_notes)


class RequestJourney:
    """One request's lifecycle through the serving path (module doc)."""

    __slots__ = ("request_id", "trace_id", "parent_span_id", "span_id",
                 "tenant", "tier", "submit_t", "admitted_t",
                 "first_token_t", "done_t", "admission_verdict",
                 "admission_wait_s", "slot", "waves", "token_ticks",
                 "tokens_total", "deadline", "deadline_margin_s",
                 "outcome", "prompt_tokens", "prefix_hit_tokens",
                 "prefill_label")

    def __init__(self, request_id: str, submit_t: float,
                 trace_id: str = "", parent_span_id: str = "",
                 tenant: str = "", tier: int = 1,
                 deadline: float | None = None,
                 admission_verdict: str = "",
                 admission_wait_s: float | None = None,
                 prompt_tokens: int = 0,
                 token_ring: int = DEFAULT_TOKEN_RING):
        self.request_id = str(request_id)
        self.trace_id = str(trace_id)
        self.parent_span_id = str(parent_span_id)
        self.span_id = new_span_id()      # the journey:request span
        self.tenant = str(tenant or "")
        self.tier = int(tier)
        self.submit_t = float(submit_t)
        self.admitted_t: float | None = None
        self.first_token_t: float | None = None
        self.done_t: float | None = None
        self.admission_verdict = str(admission_verdict)
        self.admission_wait_s = admission_wait_s
        self.slot = -1
        self.waves: dict[str, int] = {}     # admit/chunk-admit/extend
        self.token_ticks: deque = deque(maxlen=int(token_ring))
        self.tokens_total = 0
        self.deadline = deadline
        self.deadline_margin_s: float | None = None
        self.outcome = ""
        self.prompt_tokens = int(prompt_tokens)
        # prompt tokens satisfied from the prefix/KV reuse cache at
        # admit (ISSUE 13): 0 = cold prefill, >0 = cached — the
        # decoder stamps it at slot assignment, and the journey's
        # spans/outcome counters carry the cached-vs-cold tag
        self.prefix_hit_tokens = 0
        # explicit population override (ISSUE 14): "" derives
        # cached/cold from prefix_hit_tokens; the disaggregated
        # serving client stamps "remote" so journeys whose prompt KV
        # was computed by a prefill runtime form their own population
        self.prefill_label = ""

    def prefill(self) -> str:
        """The journey's prefill population: the explicit label when
        set (e.g. "remote"), else cached/cold from the prefix hit."""
        return self.prefill_label or \
            ("cached" if self.prefix_hit_tokens else "cold")

    # -- lifecycle hooks (decoder clock) -------------------------------------
    def admitted(self, t: float, slot: int, kind: str = "admit") -> None:
        if self.admitted_t is None:
            self.admitted_t = float(t)
            self.slot = int(slot)
        self.wave(kind)

    def wave(self, kind: str) -> None:
        self.waves[kind] = self.waves.get(kind, 0) + 1

    def token(self, t: float) -> None:
        if self.first_token_t is None:
            self.first_token_t = float(t)
        self.token_ticks.append(float(t))
        self.tokens_total += 1

    def finish(self, t: float, outcome: str = "") -> None:
        self.done_t = float(t)
        if self.deadline is not None:
            self.deadline_margin_s = float(self.deadline) - self.done_t
        if outcome:
            self.outcome = outcome
        elif self.deadline is not None:
            self.outcome = "deadline-met" \
                if self.deadline_margin_s >= 0 else "deadline-missed"
        else:
            self.outcome = "no-deadline"

    # -- reads ---------------------------------------------------------------
    def ttft_s(self) -> float | None:
        return None if self.first_token_t is None \
            else self.first_token_t - self.submit_t

    def queue_wait_s(self) -> float | None:
        """Decoder-side queue wait (submit → slot assigned)."""
        return None if self.admitted_t is None \
            else self.admitted_t - self.submit_t

    def itl_s(self) -> float | None:
        """Mean inter-token latency over the RETAINED tick ring."""
        ticks = self.token_ticks
        if len(ticks) < 2:
            return None
        return (ticks[-1] - ticks[0]) / (len(ticks) - 1)

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "tenant": self.tenant, "tier": self.tier,
            "admission_verdict": self.admission_verdict,
            "admission_wait_s": self.admission_wait_s,
            "submit_t": self.submit_t,
            "admitted_t": self.admitted_t,
            "first_token_t": self.first_token_t,
            "done_t": self.done_t,
            "slot": self.slot, "waves": dict(self.waves),
            "token_ticks": list(self.token_ticks),
            "tokens_total": self.tokens_total,
            "prompt_tokens": self.prompt_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefill": self.prefill(),
            "ttft_s": self.ttft_s(),
            "queue_wait_s": self.queue_wait_s(),
            "itl_s": self.itl_s(),
            "deadline_margin_s": self.deadline_margin_s,
            "outcome": self.outcome,
        }

    # -- span emission -------------------------------------------------------
    def emit_spans(self, trace_source=None, proc: str = "") -> int:
        """Record the journey as child spans of its trace context:
        journey:request (the whole lifetime, parented to the frame's
        hop span) > journey:admission / journey:queue / journey:prefill
        / one journey:token per retained tick.  No-op (returns 0) when
        the tracer is disabled — per-token spans are evidence, not a
        tax the hot path always pays."""
        source = trace_source or _global_tracer
        if not source.enabled or self.done_t is None:
            return 0
        emitted = 0

        def record(name, ts, dur, args, span_id=None, parent=None):
            nonlocal emitted
            context = TraceContext(
                self.trace_id, span_id or new_span_id(),
                parent_id=self.span_id if parent is None else parent)
            source.record(name, ts, max(0.0, dur), context=context,
                          cat="journey", proc=proc, args=args)
            emitted += 1

        record("journey:request", self.submit_t,
               self.done_t - self.submit_t,
               {"request_id": self.request_id, "tenant": self.tenant,
                "outcome": self.outcome, "slot": self.slot,
                "tokens": self.tokens_total,
                "prefill": self.prefill(),
                "deadline_margin_s": self.deadline_margin_s},
               span_id=self.span_id, parent=self.parent_span_id)
        record("journey:admission", self.submit_t,
               self.admission_wait_s or 0.0,
               {"verdict": self.admission_verdict or "direct",
                "queue_wait_s": self.admission_wait_s,
                "tenant": self.tenant, "tier": self.tier})
        if self.admitted_t is not None:
            record("journey:queue", self.submit_t,
                   self.admitted_t - self.submit_t,
                   {"slot": self.slot})
            first = self.first_token_t or self.done_t
            record("journey:prefill", self.admitted_t,
                   first - self.admitted_t,
                   {"waves": dict(self.waves),
                    "prompt_tokens": self.prompt_tokens,
                    "prefix_hit_tokens": self.prefix_hit_tokens})
        for index, tick in enumerate(self.token_ticks):
            record("journey:token", tick, 0.0, {"index": index})
        return emitted


class JourneyLog:
    """Bounded ring of completed journeys for one decoder (or one
    process): finish() completes the journey, emits its spans, and
    mirrors the outcome into `journey_requests_total{tenant, outcome}`
    — the counter family the per-tenant SLO report reads deadline
    attainment from."""

    def __init__(self, name: str = "journeys", maxlen: int = 256,
                 proc: str = "",
                 registry: MetricsRegistry | None = None):
        self.name = name
        self.proc = proc or name
        self.completed: deque = deque(maxlen=int(maxlen))
        self._registry = registry or default_registry()
        self._counters: dict = {}

    def _count(self, tenant: str, outcome: str,
               prefill: str = "cold") -> None:
        key = (tenant, outcome, prefill)
        counter = self._counters.get(key)
        if counter is None:
            counter = self._registry.counter(
                "journey_requests_total",
                "completed request journeys by tenant, outcome, and "
                "cached/cold prefill",
                labels={"log": self.name,
                        "tenant": tenant or "default",
                        "outcome": outcome,
                        "prefill": prefill})
            self._counters[key] = counter
        counter.inc()

    def finish(self, journey: RequestJourney, t: float,
               outcome: str = "") -> None:
        journey.finish(t, outcome)
        self.completed.append(journey)
        self._count(journey.tenant, journey.outcome, journey.prefill())
        journey.emit_spans(proc=self.proc)

    def journey_for(self, trace_id: str) -> RequestJourney | None:
        """Newest completed journey under a trace id (the alert
        exemplar lookup; the ring is small, a scan is fine)."""
        for journey in reversed(self.completed):
            if journey.trace_id == trace_id:
                return journey
        return None

    def journeys(self, count: int | None = None) -> list:
        entries = list(self.completed)
        return entries[-count:] if count else entries


# -- per-tenant SLO aggregation ----------------------------------------------

def tenant_slo_rows(snapshots, objective: float | None = None) -> list:
    """Per-tenant SLO attainment rows from retained metrics snapshot
    documents' `snapshot` bodies (one or many — pass several to merge a
    fleet).  Shared by the Dashboard metrics pane and
    scripts/slo_report.py so both read the SAME numbers:

      [{"tenant", "completed", "deadline_met", "deadline_missed",
        "attainment" (None without deadlines), "ttft_p50_ms"...,
        "itl_p95_ms"..., "shed", "rejected", "device_bytes",
        "host_bytes", "byte_seconds", "demotions", "promotions",
        "exemplars", "met"}, ...]

    The memory columns read the KV ledger families (kv_ledger_bytes
    by tier, kv_ledger_byte_seconds, kv_ledger_moves_total by dir) —
    zero when no ledger is attached (ISSUE 20).

    TTFT sketches carrying the serving prefill label (ISSUE 13) are
    ADDITIONALLY merged per population into ttft_{cached,cold}_p50_ms /
    _p95_ms rows, so the report quotes what the prefix cache actually
    bought each tenant (the blended percentile hides a cache that only
    helps the warm half).

    `met` is the per-tenant verdict against `objective` (None =
    reporting only, every tenant passes)."""
    from .sketch import Sketch, merge_sketches

    outcomes: dict[str, dict] = {}
    sketches: dict[tuple, list] = {}      # (tenant, family) -> [Sketch]
    split_ttft: dict[tuple, list] = {}    # (tenant, prefill) -> [Sketch]
    shed: dict[str, float] = {}
    rejected: dict[str, float] = {}
    mem_bytes: dict[tuple, float] = {}    # (tenant, tier) -> bytes
    byte_seconds: dict[str, float] = {}
    moves: dict[tuple, float] = {}        # (tenant, dir) -> count

    def tenant_of(labels: dict) -> str:
        return str(labels.get("tenant") or "default")

    for snapshot in snapshots:
        for family, entry in (snapshot or {}).items():
            kind = entry.get("type", "")
            for series in entry.get("series", []):
                labels = series.get("labels", {}) or {}
                if family == "journey_requests_total":
                    tenant = tenant_of(labels)
                    outcome = str(labels.get("outcome", ""))
                    row = outcomes.setdefault(tenant, {})
                    row[outcome] = row.get(outcome, 0) + \
                        float(series.get("value", 0))
                elif kind == "sketch" and family in (
                        "serving_ttft_seconds", "serving_itl_seconds"):
                    sketch = Sketch.from_dict(series)
                    if sketch is not None:
                        key = (tenant_of(labels), family)
                        sketches.setdefault(key, []).append(sketch)
                        prefill = str(labels.get("prefill") or "")
                        if prefill and family == "serving_ttft_seconds":
                            split_ttft.setdefault(
                                (tenant_of(labels), prefill),
                                []).append(sketch)
                elif family == "admission_shed_total":
                    tenant = tenant_of(labels)
                    shed[tenant] = shed.get(tenant, 0) + \
                        float(series.get("value", 0))
                elif family == "admission_rejected_total":
                    tenant = tenant_of(labels)
                    rejected[tenant] = rejected.get(tenant, 0) + \
                        float(series.get("value", 0))
                elif family == "kv_ledger_bytes":
                    key = (tenant_of(labels),
                           str(labels.get("tier") or ""))
                    mem_bytes[key] = mem_bytes.get(key, 0) + \
                        float(series.get("value", 0))
                elif family == "kv_ledger_byte_seconds":
                    tenant = tenant_of(labels)
                    byte_seconds[tenant] = \
                        byte_seconds.get(tenant, 0) + \
                        float(series.get("value", 0))
                elif family == "kv_ledger_moves_total":
                    key = (tenant_of(labels),
                           str(labels.get("dir") or ""))
                    moves[key] = moves.get(key, 0) + \
                        float(series.get("value", 0))

    tenants = sorted(set(outcomes) | {t for t, _ in sketches}
                     | set(shed) | set(rejected)
                     | {t for t, _ in mem_bytes})
    rows = []
    for tenant in tenants:
        counts = outcomes.get(tenant, {})
        met = counts.get("deadline-met", 0)
        missed = counts.get("deadline-missed", 0)
        attainment = met / (met + missed) if (met + missed) else None
        row = {
            "tenant": tenant,
            "completed": int(sum(counts.values())),
            "deadline_met": int(met),
            "deadline_missed": int(missed),
            "attainment": attainment,
            "shed": int(shed.get(tenant, 0)),
            "rejected": int(rejected.get(tenant, 0)),
            # KV memory ledger attribution (ISSUE 20): live bytes per
            # tier, integrated footprint, and tier-move counts
            "device_bytes": int(mem_bytes.get((tenant, "device"), 0)),
            "host_bytes": int(mem_bytes.get((tenant, "host"), 0)),
            "byte_seconds": float(byte_seconds.get(tenant, 0.0)),
            "demotions": int(moves.get((tenant, "demote"), 0)),
            "promotions": int(moves.get((tenant, "promote"), 0)),
            "exemplars": [],
        }
        for family, prefix in (("serving_ttft_seconds", "ttft"),
                               ("serving_itl_seconds", "itl")):
            merged = merge_sketches(sketches.get((tenant, family), []))
            for q, suffix in ((0.5, "p50"), (0.95, "p95"),
                              (0.99, "p99")):
                value = merged.quantile(q) if merged is not None \
                    else None
                row[f"{prefix}_{suffix}_ms"] = \
                    None if value is None else value * 1000.0
            if merged is not None and prefix == "ttft":
                # dedup by trace id: ONE frame's trace fans out to a
                # request per decoder, so merged sketches legitimately
                # repeat a trace — the report wants distinct requests
                seen: set = set()
                row["exemplars"] = [
                    e[1] for e in merged.worst_exemplars(8)
                    if not (e[1] in seen or seen.add(e[1]))][:4]
        for prefill in ("cached", "cold"):
            merged = merge_sketches(
                split_ttft.get((tenant, prefill), []))
            if merged is not None:
                for q, suffix in ((0.5, "p50"), (0.95, "p95")):
                    value = merged.quantile(q)
                    row[f"ttft_{prefill}_{suffix}_ms"] = \
                        None if value is None else value * 1000.0
        row["met"] = True if objective is None or attainment is None \
            else attainment >= objective
        rows.append(row)
    return rows
