# Metrics registry: process-wide counters, gauges, and histograms.
#
# The telemetry the runtime already kept was scattered ad-hoc state —
# `pipeline.recovery_stats` dicts, `MemoryBroker.stats`, bench-side
# medians — none of it addressable by name, none of it exportable
# (SURVEY.md §5.1: the reference has no metrics surface at all).  This
# module is the one process-wide registry those surfaces migrate onto:
#
#   * Counter    — monotonically increasing count;
#   * Gauge      — a settable level (queue depth, pool occupancy);
#   * Histogram  — fixed log-spaced buckets (latencies span decades:
#                  a 100 µs handler and a 50 s device compile must both
#                  land in a resolvable bucket).
#
# Hot-path recording is LOCK-FREE: an increment is a plain `+=` on an
# instance slot (atomic enough under the GIL for diagnostics; the odd
# lost count under true concurrency is accepted, exactly like the
# pre-existing broker counters documented best-effort).  Only metric
# CREATION takes a lock — get-or-create happens once per series, at
# setup time, never per frame.
#
# `snapshot()` returns a plain-data view (JSON-able) that the exporters
# (observe/export.py) render as Prometheus text or publish on a
# control-plane topic.  Identity is (name, sorted label items): two
# callers asking for the same series share one instance, so a broker
# and its clients can aggregate into one counter family.

from __future__ import annotations

from .sketch import Sketch
from ..utils.lock import Lock

__all__ = [
    "Counter", "Gauge", "Histogram", "Sketch", "MetricsRegistry",
    "MirroredStats", "default_registry", "log_buckets",
    "DEFAULT_LATENCY_BUCKETS",
]


def log_buckets(start: float, factor: float, count: int) -> tuple:
    """`count` log-spaced bucket upper bounds: start, start*factor, ..."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("log_buckets wants start>0, factor>1, count>=1")
    bounds, value = [], float(start)
    for _ in range(count):
        bounds.append(value)
        value *= factor
    return tuple(bounds)


# 0.1 ms .. ~52 s in powers of two: one bucket family resolves an event
# handler, a wire hop, and a first-call device compile alike.
DEFAULT_LATENCY_BUCKETS = log_buckets(0.0001, 2.0, 20)


class Counter:
    """Monotonic counter.  inc() is the lock-free hot path."""
    __slots__ = ("name", "labels", "_value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self._value = 0

    def inc(self, amount=1) -> None:
        self._value += amount

    @property
    def value(self):
        return self._value


class Gauge:
    """Settable level; inc/dec for occupancy-style use."""
    __slots__ = ("name", "labels", "_value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self._value = 0

    def set(self, value) -> None:
        self._value = value

    def inc(self, amount=1) -> None:
        self._value += amount

    def dec(self, amount=1) -> None:
        self._value -= amount

    @property
    def value(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram.  observe() is the lock-free hot path:
    a linear scan over ~20 bounds (log-spaced, so the scan is short and
    branch-predictable — cheaper than bisect's call overhead at this
    size) plus two slot adds."""
    __slots__ = ("name", "labels", "bounds", "counts", "sum", "count")

    def __init__(self, name: str, labels: dict, buckets=None):
        self.name = name
        self.labels = labels
        self.bounds = tuple(float(b) for b in
                            (buckets or DEFAULT_LATENCY_BUCKETS))
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError(f"histogram {name}: buckets must ascend")
        # counts[i] = observations <= bounds[i] exclusive of earlier
        # buckets; counts[-1] = overflow (> bounds[-1])
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value) -> None:
        index = 0
        for bound in self.bounds:
            if value <= bound:
                break
            index += 1
        self.counts[index] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound of the
        bucket containing the q-th observation; overflow reports the
        last bound).  Diagnostic-grade, like the rest of the registry."""
        if not self.count:
            return 0.0
        target = q * self.count
        running = 0
        for index, bucket_count in enumerate(self.counts):
            running += bucket_count
            if running >= target:
                return self.bounds[min(index, len(self.bounds) - 1)]
        return self.bounds[-1]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram,
          "sketch": Sketch}


class MetricsRegistry:
    """Process-wide metric table: get-or-create by (name, labels)."""

    def __init__(self):
        # diagnostic lock (house rule): held only for metric CREATION
        # and snapshot copying — never on the recording hot path
        self._lock = Lock("observe.registry")
        self._metrics: dict[tuple, object] = {}
        self._types: dict[str, str] = {}
        self._help: dict[str, str] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple:
        return (name, tuple(sorted((labels or {}).items())))

    def _get_or_create(self, kind: str, name: str, help_text: str,
                       labels: dict | None, **kwargs):
        key = self._key(name, labels)
        metric = self._metrics.get(key)
        if metric is not None:
            if self._types[name] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{self._types[name]}, requested {kind}")
            return metric
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                registered = self._types.get(name)
                if registered is not None and registered != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{registered}, requested {kind}")
                metric = _KINDS[kind](name, dict(labels or {}), **kwargs)
                # _types before _metrics: the unlocked fast path reads
                # _types[name] after seeing the metric in _metrics, so
                # publication order is load-bearing under the GIL
                self._types[name] = kind
                self._metrics[key] = metric
                if help_text:
                    self._help[name] = help_text
            return metric

    def counter(self, name: str, help: str = "",
                labels: dict | None = None) -> Counter:
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: dict | None = None) -> Gauge:
        return self._get_or_create("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: dict | None = None, buckets=None) -> Histogram:
        return self._get_or_create("histogram", name, help, labels,
                                   buckets=buckets)

    def sketch(self, name: str, help: str = "",
               labels: dict | None = None, **kwargs) -> Sketch:
        """Mergeable DDSketch-style quantile sketch (observe/sketch.py):
        relative-error quantiles that MERGE across processes, with
        top-k worst trace-id exemplars — the family the serving TTFT /
        ITL surfaces live in (ISSUE 12)."""
        return self._get_or_create("sketch", name, help, labels,
                                   **kwargs)

    def value(self, name: str, labels: dict | None = None, default=0):
        """Read one series' current value without creating it."""
        metric = self._metrics.get(self._key(name, labels))
        if metric is None:
            return default
        return metric.count if isinstance(metric, Histogram) \
            else metric.value

    def series(self, name: str) -> list:
        """Every live series of one metric family, whatever its labels:
        [(labels_dict, metric), ...].  Readers that aggregate across a
        family without knowing the label sets in advance (the admission
        gate's batch_mean_wait_ms fallback, the autoscaler's signal
        extraction) use this instead of reconstructing keys."""
        with self._lock:
            return [(dict(metric.labels), metric)
                    for (metric_name, _), metric in self._metrics.items()
                    if metric_name == name]

    def snapshot(self) -> dict:
        """Plain-data view of every series, JSON-able:
        {name: {"type", "help", "series": [{"labels", ...values}]}}."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict = {}
        for (name, _), metric in items:
            entry = out.setdefault(name, {
                "type": self._types[name],
                "help": self._help.get(name, ""),
                "series": [],
            })
            labels = dict(metric.labels)
            if isinstance(metric, Histogram):
                entry["series"].append({
                    "labels": labels, "bounds": list(metric.bounds),
                    "counts": list(metric.counts),
                    "sum": metric.sum, "count": metric.count})
            elif isinstance(metric, Sketch):
                entry["series"].append({"labels": labels,
                                        **metric.to_dict()})
            else:
                entry["series"].append({"labels": labels,
                                        "value": metric.value})
        return out


class MirroredStats(dict):
    """A stats dict whose numeric increments mirror into a registry
    counter family — the migration shim for every pre-existing ad-hoc
    stats dict (pipeline.recovery_stats, MemoryBroker.stats, the chaos
    FaultPlan counters, the batching scheduler): existing `stats[k] += n`
    call sites keep working AND feed `metric{label=k, **labels}`.

    Missing keys read as 0 (collections.Counter compatibility); only
    positive numeric deltas mirror — decrements and non-numeric values
    (e.g. mqtt's last_error string) update the dict only.  Keys named
    in `skip` never mirror: high-water marks and time-sums are levels,
    not events, and would corrupt a counter family's semantics."""

    def __init__(self, initial=None, metric: str = "", help: str = "",
                 label: str = "kind", labels: dict | None = None,
                 registry: MetricsRegistry | None = None, skip=()):
        super().__init__(initial or {})
        self._metric = metric
        self._help = help
        self._label = label
        self._labels = dict(labels or {})
        self._registry = registry
        self._counters: dict = {}
        self._skip = frozenset(skip)

    def __missing__(self, key):
        return 0

    def _counter(self, key) -> Counter:
        counter = self._counters.get(key)
        if counter is None:
            registry = self._registry or default_registry()
            counter = registry.counter(
                self._metric, self._help,
                labels={**self._labels, self._label: str(key)})
            self._counters[key] = counter
        return counter

    def __setitem__(self, key, value) -> None:
        if self._metric and key not in self._skip \
                and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            old = self.get(key, 0)
            if isinstance(old, (int, float)):
                delta = value - old
                if delta > 0:
                    self._counter(key).inc(delta)
        super().__setitem__(key, value)


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry
