# Fleet health plane, part 3: the flight recorder (ISSUE 11).
#
# When a chaos soak breaches an SLO, the evidence — the spans of the
# retried hops, the metric levels around the breach, the fault the
# chaos plan injected — has usually scrolled out of every log by the
# time anyone looks.  A FlightRecorder is a per-runtime bounded ring of
# exactly that recent evidence:
#
#   * spans   — tapped off the process-wide Tracer, routed to the
#     recorder whose runtime OWNS the span's proc name (the runtime's
#     own name or one of its registered services); unclaimed spans land
#     in the first-registered recorder so nothing is silently lost;
#   * samples — periodic (engine-timer) readings of registry
#     counter/gauge values and histogram counts;
#   * logs    — records fanned in by FlightLogHandler (WARNING+ by
#     default);
#   * faults  — chaos fault events, recorded by FaultPlan at injection
#     time through the module-level record_fault() hook (no soak wiring
#     needed: registering a recorder is enough).
#
# All rings are plain deque(maxlen) — appends are GIL-atomic, no lock
# on any recording path, same best-effort discipline as the metrics
# registry.  dump() merges the rings of EVERY registered recorder in
# the process into ONE Perfetto/Chrome trace-event timeline: one pid
# per recorder, spans as complete "X" events keyed by trace id,
# samples as "C" counter tracks, faults and logs as instant events —
# so a single file answers "what was every runtime doing when the SLO
# broke".  Dumps trigger three ways: an SLO alert firing (DumpOnAlert,
# wired to HealthAggregator.on_alert — once per rule, every breach
# ships exactly one postmortem), the chaos soak's own report step, and
# on demand via the {topic_path}/0/flight control-topic RPC.
#
# Clock domains, stated honestly: spans carry perf_counter timestamps
# (the Tracer's base), samples/faults/logs carry the engine clock
# (virtual in tests).  The merge normalizes each domain to its own
# zero so the timeline is readable; cross-domain ordering is
# approximate, correlation is by trace id, not by timestamp.

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

from .metrics import MetricsRegistry, default_registry
from .tracing import SpanRecord, tracer as _global_tracer
from ..utils import get_logger, parse

__all__ = [
    "FlightRecorder", "FlightLogHandler", "DumpOnAlert",
    "FLIGHT_TOPIC_SUFFIX", "record_fault", "register", "unregister",
    "recorders", "merge", "dump",
]

FLIGHT_TOPIC_SUFFIX = "0/flight"
_DEFAULT_RING = 4096

_logger = get_logger("observe.flight")
_recorders: list["FlightRecorder"] = []


def register(recorder: "FlightRecorder") -> None:
    if recorder not in _recorders:
        _recorders.append(recorder)
    _install_tracer_tap()


def unregister(recorder: "FlightRecorder") -> None:
    if recorder in _recorders:
        _recorders.remove(recorder)


def recorders() -> list:
    return list(_recorders)


def record_fault(kind: str, topic: str = "", sender: str = "",
                 recipient: str = "", t: float | None = None) -> None:
    """Module-level fault hook: FaultPlan calls this at every injected
    fault; a no-op (one empty-list check) when no recorder is
    registered, so chaos runs without a flight recorder pay nothing."""
    if not _recorders:
        return
    if t is None:
        t = time.monotonic()
    event = (float(t), str(kind), str(topic), str(sender),
             str(recipient))
    for recorder in _recorders:
        recorder.faults.append(event)


def _tracer_tap(span: SpanRecord) -> None:
    """Route one finished span to the recorder(s) owning its proc name;
    unclaimed spans fall to the first-registered recorder."""
    if not _recorders:
        return
    claimed = False
    for recorder in _recorders:
        if span.proc and span.proc in recorder.owned_procs():
            recorder.spans.append(span)
            claimed = True
    if not claimed:
        _recorders[0].spans.append(span)


def _install_tracer_tap(trace_source=None) -> None:
    source = trace_source or _global_tracer
    if _tracer_tap not in source.taps:
        source.taps.append(_tracer_tap)


class FlightLogHandler(logging.Handler):
    """Fans WARNING+ log records into every registered recorder's log
    ring — attach to any logger tree the runtime cares about."""

    def __init__(self, level=logging.WARNING):
        super().__init__(level)

    def emit(self, record: logging.LogRecord) -> None:
        if not _recorders:
            return
        try:
            message = record.getMessage()
        except Exception:
            return
        for recorder in _recorders:
            # stamped on EACH recorder's engine clock (virtual in
            # tests), not record.created wall-epoch — the merge
            # normalizes logs in the engine domain, and an epoch
            # timestamp would land the instant decades off-timeline
            recorder.logs.append((recorder._now(), record.levelname,
                                  record.name, message))


class FlightRecorder:
    """Per-runtime evidence ring; see module doc.

    `runtime` (optional) provides proc-name ownership for span routing,
    the engine timer for periodic metric sampling
    (`sample_interval` > 0), and the control-topic RPC
    ({topic_path}/0/flight, payload "(dump <pathname>)" → merged dump
    written, reply "(dumped <pathname> <events>)" on .../flight/out).
    Without a runtime it is a bare ring the caller feeds directly."""

    def __init__(self, runtime=None, name: str | None = None,
                 maxlen: int = _DEFAULT_RING,
                 sample_interval: float = 0.0, families=None,
                 registry: MetricsRegistry | None = None,
                 rpc: bool = True):
        self.runtime = runtime
        self.name = name or (getattr(runtime, "name", None) or "flight")
        self.registry = registry or default_registry()
        self.families = set(families) if families is not None else None
        self.spans: deque = deque(maxlen=maxlen)
        self.samples: deque = deque(maxlen=maxlen)
        self.logs: deque = deque(maxlen=maxlen)
        self.faults: deque = deque(maxlen=maxlen)
        self._timer = None
        self._rpc_topic = None
        self._dump_worker = None
        if runtime is not None and sample_interval > 0:
            self._timer = runtime.event.add_timer_handler(
                self.sample_now, float(sample_interval))
        if runtime is not None and rpc:
            self._rpc_topic = \
                f"{runtime.topic_path}/{FLIGHT_TOPIC_SUFFIX}"
            runtime.add_message_handler(self._rpc_handler,
                                        self._rpc_topic)
        register(self)

    def _now(self) -> float:
        """This recorder's engine-domain clock (monotonic fallback for
        bare recorders) — samples/faults/logs all stamp with it."""
        return self.runtime.event.clock.now() if self.runtime \
            is not None else time.monotonic()

    def owned_procs(self) -> set:
        """Proc names this recorder claims spans for: the runtime's own
        name plus every registered service's (pipelines and actors
        record spans under their service name, not the runtime's)."""
        if self.runtime is None:
            return {self.name}
        owned = {self.runtime.name}
        for service in self.runtime.services().values():
            service_name = getattr(service, "name", None)
            if service_name:
                owned.add(service_name)
        return owned

    # -- recording ----------------------------------------------------------
    def record_span(self, span: SpanRecord) -> None:
        self.spans.append(span)

    def record_sample(self, t: float, key: str, value) -> None:
        self.samples.append((float(t), str(key), value))

    def record_log(self, t: float, level: str, logger_name: str,
                   message: str) -> None:
        self.logs.append((float(t), level, logger_name, message))

    def record_fault(self, t: float, kind: str, topic: str = "",
                     sender: str = "", recipient: str = "") -> None:
        self.faults.append((float(t), kind, topic, sender, recipient))

    def sample_now(self) -> None:
        """One registry sweep into the sample ring: counter/gauge
        values and histogram observation counts, keyed
        'family{labels}' (observe.export.series_key form)."""
        from .export import series_key
        t = self._now()
        for name, entry in self.registry.snapshot().items():
            if self.families is not None and name not in self.families:
                continue
            for series in entry.get("series", []):
                key = series_key(name, series.get("labels", {}))
                # self.samples is a deque(maxlen=...) sized at
                # construction — bounded by the ring, shed-oldest
                if entry.get("type") == "histogram":
                    self.samples.append(  # graft: disable=lint-unbounded-queue
                        (t, f"{key}:count", series.get("count", 0)))
                else:
                    self.samples.append(  # graft: disable=lint-unbounded-queue
                        (t, key, series.get("value", 0)))

    # -- RPC ----------------------------------------------------------------
    def _rpc_handler(self, _topic, payload) -> None:
        try:
            if isinstance(payload, (bytes, bytearray)):
                payload = payload.decode("utf-8")
            command, params = parse(payload)
        except Exception:
            return
        if command != "dump" or not params:
            return
        pathname = str(params[0])
        # the merged dump is synchronous file I/O — seconds for a full
        # ring — so it runs on a worker thread, not the event loop;
        # the reply publishes from that thread, which is the same
        # off-loop delivery the MQTT network thread already does
        worker = threading.Thread(
            target=self._dump_and_reply,
            args=(pathname, f"{self._rpc_topic}/out"),
            name=f"flight-dump:{self.name}", daemon=True)
        self._dump_worker = worker
        worker.start()

    def _dump_and_reply(self, pathname: str, reply_topic: str) -> None:
        try:
            dump(pathname)
            events = sum(len(r.spans) + len(r.samples) + len(r.faults)
                         + len(r.logs) for r in _recorders)
            self.runtime.publish(reply_topic,
                                 f"(dumped {pathname} {events})")
        except Exception:
            _logger.exception("flight %s: RPC dump to %s failed",
                              self.name, pathname)

    def close(self) -> None:
        if self.runtime is not None:
            if self._timer is not None:
                self.runtime.event.remove_timer_handler(self._timer)
                self._timer = None
            if self._rpc_topic is not None:
                self.runtime.remove_message_handler(self._rpc_handler,
                                                    self._rpc_topic)
                self._rpc_topic = None
        unregister(self)


# -- merged dump --------------------------------------------------------------

def _span_events(recorder: FlightRecorder, pid: int, t0: float) -> list:
    events, seen = [], set()
    for span in list(recorder.spans):
        key = (span.trace_id, span.span_id, span.name, span.ts)
        if key in seen:
            continue
        seen.add(key)
        args = {"trace_id": span.trace_id, "span_id": span.span_id,
                "parent_id": span.parent_id, "proc": span.proc}
        args.update(span.args)
        events.append({
            "name": span.name, "cat": span.cat or "span", "ph": "X",
            "ts": round((span.ts - t0) * 1e6, 3),
            "dur": max(round(span.dur * 1e6, 3), 0.001),
            "pid": pid, "tid": 1, "args": args,
        })
    return events


def merge(recorder_list=None) -> dict:
    """Merge every recorder's rings into one Chrome trace-event
    document (Perfetto-loadable): one pid per recorder, spans "X",
    samples "C", faults/logs instant "i".  Each clock domain is
    normalized to its own zero (module doc)."""
    sources = recorder_list if recorder_list is not None \
        else list(_recorders)
    events: list[dict] = []
    span_t0 = min((s.ts for r in sources for s in r.spans),
                  default=0.0)
    engine_ts = [e[0] for r in sources
                 for ring in (r.samples, r.faults, r.logs)
                 for e in ring]
    engine_t0 = min(engine_ts, default=0.0)
    for index, recorder in enumerate(sources):
        pid = index + 1
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0,
                       "args": {"name": recorder.name}})
        events.extend(_span_events(recorder, pid, span_t0))
        for t, key, value in list(recorder.samples):
            events.append({"name": key, "ph": "C", "pid": pid,
                           "ts": round((t - engine_t0) * 1e6, 3),
                           "args": {"value": value}})
        for t, kind, topic, sender, recipient in list(recorder.faults):
            events.append({"name": f"fault:{kind}", "ph": "i",
                           "s": "g", "pid": pid, "tid": 1,
                           "ts": round((t - engine_t0) * 1e6, 3),
                           "args": {"topic": topic, "sender": sender,
                                    "recipient": recipient}})
        for t, level, logger_name, message in list(recorder.logs):
            events.append({"name": f"log:{level}", "ph": "i", "s": "t",
                           "pid": pid, "tid": 1,
                           "ts": round((t - engine_t0) * 1e6, 3),
                           "args": {"logger": logger_name,
                                    "message": message}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump(pathname, recorder_list=None, reason: str = "",
         metadata: dict | None = None) -> str:
    """Write the merged timeline to `pathname`; returns the path.
    `metadata` merges extra keys into the document's metadata block —
    DumpOnAlert ships the firing alert's exemplar trace ids there, so
    the artifact itself names which traces to open first."""
    document = merge(recorder_list)
    if reason or metadata:
        document["metadata"] = {**({"reason": reason} if reason
                                   else {}), **(metadata or {})}
    with open(pathname, "w", encoding="utf-8") as f:
        json.dump(document, f)
    _logger.info("flight recorder dump -> %s (%d events%s)", pathname,
                 len(document["traceEvents"]),
                 f", reason={reason}" if reason else "")
    return str(pathname)


class DumpOnAlert:
    """HealthAggregator.on_alert callback: writes ONE merged dump per
    rule into `directory` — every breach ships exactly one postmortem
    artifact, however many evaluation ticks it stays breached (the
    aggregator already edge-triggers; the latch here also survives
    flapping rules re-firing)."""

    def __init__(self, directory, prefix: str = "flight"):
        self.directory = str(directory)
        self.prefix = prefix
        self.dumped: dict[str, str] = {}       # rule name -> path

    def __call__(self, rule, record) -> str | None:
        name = getattr(rule, "name", str(rule))
        if name in self.dumped:
            return None
        pathname = f"{self.directory}/{self.prefix}_{name}.json"
        exemplars = (record or {}).get("exemplars") or []
        self.dumped[name] = dump(
            pathname, reason=f"slo-breach:{name}",
            metadata={"exemplars": list(exemplars)} if exemplars
            else None)
        return self.dumped[name]
