# KV memory ledger: cross-tier byte attribution, always-on leak
# auditing, and capacity pressure signals (ISSUE 20, observability
# tentpole).
#
# The serving plane already publishes per-component memory gauges —
# kv_pool_blocks_used (device), kv_host_bytes (host tier), the prefix
# cache's budget counters — but nothing ATTRIBUTES those bytes to
# tenants, nothing checks that the per-component numbers agree with
# each other, and the zero-leak invariants live only in test-time
# audits.  The ledger is the single accounting surface every
# block-lifecycle seam reports through:
#
#   * BlockPool alloc/release call device_delta with the owning
#     tenant, so ledger device totals conserve against
#     used_blocks() * block_nbytes BY CONSTRUCTION — retains and
#     refcount handoffs (prefix aliasing, harvest, install_chain)
#     change ownership, not bytes, and stay invisible here;
#   * HostBlockStore put/evict/pop_promoted call host_delta at the
#     exact points its own _tenant_bytes move, plus move() counters
#     for the demotion/promotion flows r06 quotes;
#   * the dense (unpaged) PrefixKVCache charges insert/evict bytes
#     directly — device tier truth is then cache.bytes_used;
#   * violations (double-release, conservation drift, host orphans)
#     count kv_ledger_violations_total, latch the
#     kv_ledger_violations level gauge (the HealthAggregator rule
#     target), and record a flight-recorder fault carrying the
#     offending chain key — the DumpOnAlert postmortem then names the
#     leaked chain, not just "a leak happened".
#
# audit() runs on an engine timer (attach_engine), so the leak checks
# that used to exist only in tests run continuously in production.
# Engine callbacks are serialized, which is why transient intra-call
# imbalances (install_chain's alloc -> insert -> release handoff)
# can never be observed by the auditor.
#
# Families (all labelled {ledger}):
#   kv_ledger_bytes{tier, tenant}         live attribution
#   kv_ledger_pinned_bytes{tenant}        device bytes with live refs
#   kv_ledger_byte_seconds{tenant}        integrated footprint cost
#   kv_ledger_events_total{kind}          lifecycle event counts
#   kv_ledger_moves_total{tenant, dir}    demote / promote flows
#   kv_ledger_violations                  latched violation level
#   kv_ledger_violations_total{kind}      violations by kind
#   kv_ledger_host_pressure               host bytes_used / max_bytes

from __future__ import annotations

import collections
import time

from .metrics import MirroredStats, default_registry
from ..utils import get_logger

__all__ = ["KVMemoryLedger", "assert_ledger_clean", "seed_ledger_leak"]

_EVENT_KINDS = (
    "alloc", "release", "cow", "cache_insert", "cache_evict",
    "demote", "host_evict", "promote", "install", "migrate_out",
    "migrate_in", "session_pin", "session_demote", "lease_pin",
    "lease_demote",
)


def _tenant_key(tenant) -> str:
    return str(tenant or "default")


class KVMemoryLedger:
    """Per-tenant, per-tier KV byte accounting + invariant auditor.

    Event-loop single-threaded like everything it attaches to; every
    seam guards `if ledger is not None`, so an un-ledgered serving
    stack pays nothing."""

    def __init__(self, name: str = "kv", registry=None, clock=None,
                 trend_window: float = 30.0,
                 max_violations: int = 64):
        self.name = str(name)
        self.logger = get_logger(f"observe.ledger.{name}")
        self._registry = registry or default_registry()
        self._clock = clock or time.monotonic
        self.trend_window = float(trend_window)
        # per-tenant balances (bytes); zero balances are dropped,
        # negative ones kept visible for the auditor
        self._device: dict = {}
        self._host: dict = {}
        self._byte_seconds: dict = {}
        self._accrued_at: dict = {}
        # attached components (audit truth sources)
        self._pool = None
        self._store = None
        self._cache = None
        self._engine = None
        self._timer = None
        # occupancy trend ring: (t, device total bytes)
        self._occupancy = collections.deque(maxlen=256)
        self.violations: collections.deque = collections.deque(
            maxlen=max(1, int(max_violations)))
        self._violation_total = 0
        self._open: set = set()     # audit findings currently standing
        self.stats = MirroredStats(
            {kind: 0 for kind in _EVENT_KINDS},
            metric="kv_ledger_events_total",
            help="KV ledger lifecycle events by kind",
            registry=self._registry,
            labels={"ledger": self.name})
        self._gauge_violations = self._registry.gauge(
            "kv_ledger_violations",
            "latched count of ledger invariant violations",
            labels={"ledger": self.name})
        self._gauge_pressure = self._registry.gauge(
            "kv_ledger_host_pressure",
            "host tier bytes_used / max_bytes",
            labels={"ledger": self.name})
        self._tier_gauges: dict = {}
        self._pinned_gauges: dict = {}
        self._bs_gauges: dict = {}
        self._move_counters: dict = {}
        self._violation_counters: dict = {}

    # -- attachment --------------------------------------------------------
    def attach_pool(self, pool) -> None:
        """Adopt a BlockPool as the device-tier truth source (the pool
        reports through device_delta once its attach_ledger is set —
        callers use pool.attach_ledger(ledger), which calls back)."""
        self._pool = pool

    def attach_host(self, store) -> None:
        self._store = store

    def attach_cache(self, cache) -> None:
        self._cache = cache

    def attach_engine(self, engine, interval: float = 1.0) -> None:
        """Run audit() on an engine timer — the always-on promotion of
        the test-time leak checks."""
        self.detach_engine()
        self._engine = engine
        self._timer = engine.add_timer_handler(self.audit,
                                               float(interval))

    def detach_engine(self) -> None:
        if self._engine is not None and self._timer is not None:
            self._engine.remove_timer_handler(self._timer)
        self._engine = None
        self._timer = None

    # -- event API ---------------------------------------------------------
    def device_delta(self, tenant, nbytes: int, kind: str = "") -> None:
        """One physical device-tier transition: positive on alloc /
        dense insert, negative on the refs 1->0 release / dense evict.
        Refcount handoffs never call this."""
        self._delta(self._device, "device", tenant, nbytes, kind)
        self._note_occupancy()

    def host_delta(self, tenant, nbytes: int, kind: str = "") -> None:
        self._delta(self._host, "host", tenant, nbytes, kind)

    def _delta(self, balances: dict, tier: str, tenant,
               nbytes: int, kind: str) -> None:
        tenant = _tenant_key(tenant)
        self._accrue(tenant)
        total = balances.get(tenant, 0) + int(nbytes)
        if total:
            balances[tenant] = total
        else:
            balances.pop(tenant, None)
        if total < 0:
            self.violation(
                "negative-balance", tenant=tenant,
                detail=f"{tier} balance {total} after {kind or 'delta'}"
                       f" of {int(nbytes)}")
        self._tier_gauge(tier, tenant).set(total)
        if kind:
            self.stats[kind] += 1

    def event(self, kind: str, count: int = 1) -> None:
        """Count a lifecycle event with no byte movement (session
        pins, migration shipments)."""
        self.stats[kind] += int(count)

    def move(self, tenant, direction: str, count: int = 1) -> None:
        """Count a cross-tier move (direction: demote | promote) for
        the per-tenant flow columns in the SLO report."""
        tenant = _tenant_key(tenant)
        counter = self._move_counters.get((tenant, direction))
        if counter is None:
            counter = self._registry.counter(
                "kv_ledger_moves_total",
                "cross-tier KV block moves by tenant and direction",
                labels={"ledger": self.name, "tenant": tenant,
                        "dir": direction})
            self._move_counters[(tenant, direction)] = counter
        counter.inc(int(count))

    def violation(self, kind: str, tenant: str = "",
                  chain_key: str = "", detail: str = "") -> dict:
        """Record one invariant violation: bounded deque + counters +
        latched level gauge + a flight-recorder fault that carries the
        offending chain key into the DumpOnAlert postmortem (the
        level-rule alert record itself has no sketch exemplars — the
        fault ring is how the key reaches the dump)."""
        self._violation_total += 1
        record = {"kind": kind, "tenant": tenant,
                  "chain_key": chain_key, "detail": detail,
                  "t": self._clock()}
        self.violations.append(record)
        counter = self._violation_counters.get(kind)
        if counter is None:
            counter = self._registry.counter(
                "kv_ledger_violations_total",
                "ledger invariant violations by kind",
                labels={"ledger": self.name, "kind": kind})
            self._violation_counters[kind] = counter
        counter.inc()
        self._gauge_violations.set(self._violation_total)
        from . import flight
        flight.record_fault(f"ledger-{kind}",
                            topic=chain_key or tenant)
        self.logger.warning(
            "ledger %s: %s violation tenant=%r chain=%r %s",
            self.name, kind, tenant, chain_key, detail)
        return record

    # -- queries -----------------------------------------------------------
    def device_bytes(self, tenant=None) -> int:
        if tenant is None:
            return sum(self._device.values())
        return self._device.get(_tenant_key(tenant), 0)

    def host_bytes(self, tenant=None) -> int:
        if tenant is None:
            return sum(self._host.values())
        return self._host.get(_tenant_key(tenant), 0)

    def byte_seconds(self, tenant=None) -> float:
        if tenant is None:
            return float(sum(self._byte_seconds.values()))
        return float(self._byte_seconds.get(_tenant_key(tenant), 0.0))

    def tenants(self) -> list:
        return sorted(set(self._device) | set(self._host))

    def host_pressure(self) -> float:
        store = self._store
        if store is None or not getattr(store, "max_bytes", None):
            return 0.0
        return store.bytes_used / store.max_bytes

    def device_trend(self, window: float | None = None) -> float | None:
        """Device-footprint slope in bytes/second over the trend
        window — the relief-rate input to byte-aware admission (a
        negative trend means blocks are draining)."""
        window = self.trend_window if window is None else float(window)
        now = self._clock()
        samples = [(t, b) for t, b in self._occupancy
                   if now - t <= window]
        if len(samples) < 2:
            return None
        (t0, b0), (t1, b1) = samples[0], samples[-1]
        if t1 <= t0:
            return None
        return (b1 - b0) / (t1 - t0)

    def pinned_bytes(self, tenant) -> int:
        """Device bytes the tenant cannot currently evict: total minus
        the prefix cache's refs==0 (evictable) bytes.  Slot-resident
        blocks are pinned by definition — they are not in the cache."""
        tenant = _tenant_key(tenant)
        total = self._device.get(tenant, 0)
        cache = self._cache
        if cache is None or total <= 0:
            return max(0, total)
        evictable = cache.evictable_bytes(tenant)
        return max(0, total - evictable)

    # -- accrual / trend ---------------------------------------------------
    def _accrue(self, tenant: str) -> None:
        now = self._clock()
        last = self._accrued_at.get(tenant)
        if last is not None and now > last:
            resident = self._device.get(tenant, 0) + \
                self._host.get(tenant, 0)
            if resident > 0:
                total = self._byte_seconds.get(tenant, 0.0) + \
                    resident * (now - last)
                self._byte_seconds[tenant] = total
                self._bs_gauge(tenant).set(total)
        self._accrued_at[tenant] = now

    def _note_occupancy(self) -> None:
        self._occupancy.append(
            (self._clock(), sum(self._device.values())))

    # -- gauge caches ------------------------------------------------------
    def _tier_gauge(self, tier: str, tenant: str):
        gauge = self._tier_gauges.get((tier, tenant))
        if gauge is None:
            gauge = self._registry.gauge(
                "kv_ledger_bytes",
                "KV bytes attributed by tier and tenant",
                labels={"ledger": self.name, "tier": tier,
                        "tenant": tenant})
            self._tier_gauges[(tier, tenant)] = gauge
        return gauge

    def _pinned_gauge(self, tenant: str):
        gauge = self._pinned_gauges.get(tenant)
        if gauge is None:
            gauge = self._registry.gauge(
                "kv_ledger_pinned_bytes",
                "device KV bytes with live references by tenant",
                labels={"ledger": self.name, "tenant": tenant})
            self._pinned_gauges[tenant] = gauge
        return gauge

    def _bs_gauge(self, tenant: str):
        gauge = self._bs_gauges.get(tenant)
        if gauge is None:
            gauge = self._registry.gauge(
                "kv_ledger_byte_seconds",
                "integrated KV byte-seconds by tenant",
                labels={"ledger": self.name, "tenant": tenant})
            self._bs_gauges[tenant] = gauge
        return gauge

    # -- the auditor -------------------------------------------------------
    def audit(self) -> list:
        """One invariant sweep (engine-timer driven in production):
        conservation against the component truth sources, the pool's
        gauge twin, host-tier internal accounting, and negative
        balances.  Standing findings are deduplicated — a persistent
        drift fires ONE violation when it appears, not one per tick.
        Returns the new violation records."""
        for tenant in list(set(self._device) | set(self._host)):
            self._accrue(tenant)
        self._note_occupancy()
        found: dict = {}
        pool, store, cache = self._pool, self._store, self._cache
        if pool is not None:
            used = pool.used_blocks()
            if used != pool._used:
                found[("gauge-drift", "", "")] = (
                    f"pool {pool.name}: incremental used {pool._used} "
                    f"!= refs scan {used}")
            expected = used * pool.block_nbytes
            if self.device_bytes() != expected:
                found[("device-conservation", "", "")] = (
                    f"ledger device {self.device_bytes()} != pool "
                    f"{expected} ({used} blocks)")
        elif cache is not None and getattr(cache, "pool", None) is None:
            if self.device_bytes() != cache.bytes_used:
                found[("device-conservation", "", "")] = (
                    f"ledger device {self.device_bytes()} != cache "
                    f"bytes_used {cache.bytes_used}")
        if store is not None:
            if self.host_bytes() != store.bytes_used:
                found[("host-conservation", "", "")] = (
                    f"ledger host {self.host_bytes()} != store "
                    f"bytes_used {store.bytes_used}")
            recomputed: dict = {}
            newest: dict = {}
            for node in store._nodes.values():
                recomputed[node.tenant] = \
                    recomputed.get(node.tenant, 0) + node.nbytes
                newest[node.tenant] = node.key
            for tenant in set(recomputed) | set(store._tenant_bytes):
                if recomputed.get(tenant, 0) != \
                        store._tenant_bytes.get(tenant, 0):
                    # the newest entry for the tenant is the orphan in
                    # every seeded/realistic case (accounting is
                    # updated with insertion, so drift names the
                    # latest arrival)
                    found[("host-orphan", tenant,
                           newest.get(tenant, ""))] = (
                        f"store {store.name}: tenant {tenant} nodes "
                        f"sum {recomputed.get(tenant, 0)} != recorded "
                        f"{store._tenant_bytes.get(tenant, 0)}")
                ledger_side = self._host.get(tenant, 0)
                if store._tenant_bytes.get(tenant, 0) != ledger_side:
                    found[("host-conservation", tenant, "")] = (
                        f"ledger host[{tenant}] {ledger_side} != "
                        f"store {store._tenant_bytes.get(tenant, 0)}")
        for balances in (self._device, self._host):
            for tenant, total in balances.items():
                if total < 0:
                    found[("negative-balance", tenant, "")] = (
                        f"balance {total}")
        new = []
        for (kind, tenant, chain_key), detail in found.items():
            if (kind, tenant, chain_key) in self._open:
                continue
            new.append(self.violation(kind, tenant=tenant,
                                      chain_key=chain_key,
                                      detail=detail))
        self._open = set(found)
        # level publishes every tick (pressure + pinned split are
        # lazy: computed here, not event-driven)
        self._gauge_pressure.set(self.host_pressure())
        if cache is not None:
            for tenant in list(self._device):
                self._pinned_gauge(tenant).set(
                    self.pinned_bytes(tenant))
        return new


def _check(condition, message: str) -> None:
    # explicit raise, not `assert`: the audit must keep holding under
    # python -O (AssertionError so pytest renders it like a test
    # assertion)
    if not condition:
        raise AssertionError(message)


def assert_ledger_clean(pool=None, store=None, cache=None,
                        ledger=None, empty: bool = True) -> None:
    """The shared leak audit (ISSUE 20 satellite): the assertions the
    paged / tiered / drain-migrate tests used to carry inline, behind
    one seam.  With empty=True (the post-drain default) every tier
    must be at zero; empty=False checks only the internal-consistency
    invariants (gauge twins, cross-structure conservation)."""
    if cache is not None:
        pool = pool if pool is not None \
            else getattr(cache, "pool", None)
        store = store if store is not None \
            else getattr(cache, "host_store", None)
    if pool is not None:
        used = pool.used_blocks()
        _check(pool._used == used,
               f"pool {pool.name}: gauge twin {pool._used} != {used}")
        free_ids = set(pool._free)
        _check(len(free_ids) == len(pool._free),
               f"pool {pool.name}: duplicate ids on the free list")
        if empty:
            _check(used == 0,
                   f"pool {pool.name}: {used} blocks still owned")
            _check(len(pool._free) == pool.num_blocks - 1,
                   f"pool {pool.name}: free list {len(pool._free)} "
                   f"!= {pool.num_blocks - 1}")
    if cache is not None:
        recomputed = sum(node.nbytes
                         for node in cache._nodes.values())
        _check(cache.bytes_used == recomputed,
               f"cache bytes_used {cache.bytes_used} != nodes "
               f"{recomputed}")
        if empty:
            _check(cache.bytes_used == 0,
                   f"cache holds {cache.bytes_used} bytes")
            _check(not cache._nodes,
                   f"cache holds {len(cache._nodes)} nodes")
    if store is not None:
        recomputed = sum(node.nbytes
                         for node in store._nodes.values())
        _check(store.bytes_used == recomputed,
               f"store {store.name}: bytes_used {store.bytes_used} "
               f"!= nodes {recomputed}")
        _check(store.bytes_used == sum(store._tenant_bytes.values()),
               f"store {store.name}: tenant split disagrees with "
               f"total")
        if empty:
            _check(store.bytes_used == 0 and not store._nodes,
                   f"store {store.name}: {len(store._nodes)} host "
                   f"blocks still resident")
    if ledger is not None:
        ledger.audit()
        _check(not ledger._open,
               f"ledger {ledger.name}: audit found "
               f"{sorted(ledger._open)}")
        if empty:
            _check(ledger.device_bytes() == 0,
                   f"ledger device tier {ledger.device_bytes()}")
            _check(ledger.host_bytes() == 0,
                   f"ledger host tier {ledger.host_bytes()}")


def seed_ledger_leak(cache=None, store=None,
                     kind: str = "double-release",
                     key: str | None = None) -> str:
    """Chaos hook: deliberately break one ledger invariant so the
    always-on auditor's detection -> alert -> postmortem path can be
    exercised end to end.  Returns the chain key the violation will
    carry (the thing the flight dump must name).

    kinds:
      double-release — release a prefix-cache chain key whose refs are
        already zero (the classic paired-release bug);
      orphan-host — register a host block bypassing the store's byte
        accounting (the classic forgotten-accounting bug)."""
    if kind == "double-release":
        if cache is None:
            raise ValueError("double-release needs the prefix cache")
        if key is None:
            for node_key, node in cache._nodes.items():
                if node.refs == 0:
                    key = node_key
                    break
        if key is None:
            raise ValueError("no refs==0 cached chain to re-release")
        cache.release([key])
        return key

    if kind == "orphan-host":
        if store is None:
            raise ValueError("orphan-host needs the host store")
        donor = next(reversed(store._nodes.values()), None) \
            if store._nodes else None

        class _Orphan:
            pass

        orphan = _Orphan()
        orphan.key = key or "orphan-chain"
        orphan.parent = donor.key if donor is not None else ""
        orphan.tenant = donor.tenant if donor is not None \
            else "default"
        orphan.k_rows = donor.k_rows if donor is not None else []
        orphan.v_rows = donor.v_rows if donor is not None else []
        orphan.nbytes = donor.nbytes if donor is not None else 4096
        store._nodes[orphan.key] = orphan      # bytes NOT accounted
        return orphan.key

    raise ValueError(f"unknown leak kind {kind!r}")
