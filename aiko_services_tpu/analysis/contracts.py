# Per-edge dtype/shape/codec contract grammar.
#
# An element may declare, per input/output name, what it produces or
# accepts:
#
#     "f32[*,80]"                  float32 array, any leading dim, 80 mels
#     "f32[*] | i16[*]"            either dtype, rank-1 any length
#     "f32[*] | mulaw-u8[*]"       raw float audio OR µ-law codes (uint8)
#     "str"                        a python string
#     "any"                        no constraint (explicit opt-out)
#
#     contract  := alt ("|" alt)*
#     alt       := [codec "-"] dtype [ "[" dims "]" ]
#     dims      := dim ("," dim)*     dim := integer | "*"
#
# Codec prefixes name the wire codecs from transport/wire.py (mulaw, i8,
# dct8): "mulaw-u8" reads "uint8 values that are µ-law codes".  Producer
# and consumer are compatible when ANY producer alternative matches ANY
# consumer alternative (same codec, dtype equal or `any`, shapes
# unifiable dim-by-dim with `*` as wildcard; a missing shape suffix
# matches every shape).

from __future__ import annotations

from dataclasses import dataclass

from ..transport.wire import WIRE_CODECS

__all__ = ["Alt", "ContractError", "parse_contract", "compatible",
           "DTYPE_ALIASES"]

DTYPE_ALIASES = {
    "f16": "float16", "f32": "float32", "f64": "float64",
    "bf16": "bfloat16",
    "i8": "int8", "i16": "int16", "i32": "int32", "i64": "int64",
    "u8": "uint8", "u16": "uint16", "u32": "uint32", "u64": "uint64",
    "bool": "bool", "str": "str", "bytes": "bytes", "any": "any",
}
_CANONICAL = set(DTYPE_ALIASES.values())


class ContractError(ValueError):
    """Raised when a contract string does not parse."""


@dataclass(frozen=True)
class Alt:
    """One alternative of a contract: optional codec + dtype + shape.

    shape is None (unconstrained) or a tuple whose entries are ints or
    the wildcard string "*"."""
    codec: str              # "" = uncoded
    dtype: str              # canonical numpy-style name, "str", or "any"
    shape: tuple | None

    def __str__(self) -> str:
        text = f"{self.codec}-{self.dtype}" if self.codec else self.dtype
        if self.shape is not None:
            text += "[" + ",".join(str(d) for d in self.shape) + "]"
        return text


def _parse_alt(token: str) -> Alt:
    text = token.strip()
    if not text:
        raise ContractError("empty contract alternative")
    codec = ""
    if "-" in text:
        codec, rest = text.split("-", 1)
        codec = codec.strip()
        if codec not in WIRE_CODECS:
            raise ContractError(
                f"unknown wire codec {codec!r} in {token!r} "
                f"(known: {sorted(WIRE_CODECS)})")
        text = rest.strip()
    shape: tuple | None = None
    if "[" in text:
        if not text.endswith("]"):
            raise ContractError(f"unterminated shape in {token!r}")
        text, dims_text = text[:-1].split("[", 1)
        text = text.strip()
        dims = []
        for dim in dims_text.split(","):
            dim = dim.strip()
            if dim == "*":
                dims.append("*")
            elif dim.isdigit():
                dims.append(int(dim))
            else:
                raise ContractError(
                    f"bad shape dim {dim!r} in {token!r} "
                    f"(expected integer or *)")
        shape = tuple(dims)
    dtype = DTYPE_ALIASES.get(text, text if text in _CANONICAL else None)
    if dtype is None:
        raise ContractError(
            f"unknown dtype {text!r} in {token!r} "
            f"(expected one of {sorted(DTYPE_ALIASES)})")
    if codec and dtype in ("str", "any"):
        raise ContractError(
            f"codec {codec!r} cannot qualify dtype {dtype!r} in {token!r}")
    return Alt(codec, dtype, shape)


def parse_contract(text: str) -> list[Alt]:
    """Parse "alt | alt | ..." into its alternatives; raises
    ContractError on any syntax problem."""
    if not isinstance(text, str) or not text.strip():
        raise ContractError(f"contract must be a non-empty string, "
                            f"got {text!r}")
    return [_parse_alt(token) for token in text.split("|")]


def _shapes_unify(a: tuple | None, b: tuple | None) -> bool:
    if a is None or b is None:
        return True
    if len(a) != len(b):
        return False
    return all(x == "*" or y == "*" or x == y for x, y in zip(a, b))


def _alts_match(produced: Alt, accepted: Alt) -> bool:
    if produced.codec != accepted.codec:
        return False
    if "any" not in (produced.dtype, accepted.dtype) and \
            produced.dtype != accepted.dtype:
        return False
    return _shapes_unify(produced.shape, accepted.shape)


def compatible(producer: list[Alt], consumer: list[Alt]) -> bool:
    """True when some producer alternative satisfies some consumer
    alternative."""
    return any(_alts_match(p, c) for p in producer for c in consumer)
