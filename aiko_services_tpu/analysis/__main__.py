# `python -m aiko_services_tpu.analysis ...` — the graft-check CLI.

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
