# Interprocedural effect analysis (ISSUE 18 tentpole).
#
# The syntactic lint rules go blind the moment the offending call is
# one helper deep: `process_frame` calling `self._flush()` which calls
# `time.sleep` passes lint-blocking-call.  This pass closes that hole:
#
#   1. collect per-function DIRECT effects —
#        blocks     time.sleep / blocking attrs / subprocess / select /
#                   socket.create_connection / builtin open()
#        transfers  jax.device_get / jax.device_put, and the pool-row
#                   transfer pattern lint-host-transfer matches
#        allocates  np/jnp array constructors (lint-hot-alloc's set)
#        wall_clock the lint-wall-clock canonical call set
#        locks      acquire sites (with-lock / .acquire) plus the
#                   ordering edges they imply
#   2. propagate effects transitively over the call graph to a
#      fixpoint, recording one WITNESS per (function, effect): either
#      the direct leaf site or the call edge the effect arrived
#      through — so every finding can print its provenance chain
#   3. report at the ROOTS: event-loop contexts (frame methods +
#      add_*_handler registrations, package-wide) for blocks /
#      transfers / wall_clock, `graft: hot-path` functions for
#      allocates / transfers — using the SAME rule ids as the
#      syntactic rules, but only for chains of depth ≥ 1 (depth 0 is
#      the syntactic rule's finding; reporting it twice would be noise)
#
# Waivers are honored at ANY frame: a `graft: disable=<rule>` comment
# on the leaf line kills the effect at the source, on an intermediate
# call line severs that edge, and on the root's `def` line silences
# the root — all resolved by statement extent via WaiverIndex, all
# recorded in the shared WaiverLog so the stale-waiver audit sees them.
#
# Lock-order edges: `with lockA:` whose body (transitively) acquires
# lockB yields a static edge A→B, the same relation the runtime
# AIKO_LOCK_CHECK detector builds from actual acquisitions; a static
# cycle is reported as a `lint-lock-order` warning with both edges'
# provenance.

from __future__ import annotations

import ast

from .callgraph import PackageGraph, build_graph
from .findings import ERROR, WARNING, Finding
from .lint import (_ALLOC_MODULES, _ALLOC_TAILS, _BLOCKING_ATTRS,
                   _POOL_ROW_TOKENS, _TRANSFER_MODULES, _TRANSFER_TAILS,
                   _WALL_CLOCK_CALLS, WaiverLog, _func_tail,
                   _is_test_path, _mentions_lock)

__all__ = ["EffectAnalysis", "effect_findings", "EFFECT_RULES"]

# effect kind -> the lint rule id its findings (and waivers) use
EFFECT_RULES = {
    "blocks": "lint-blocking-call",
    "transfers": "lint-host-transfer",
    "allocates": "lint-hot-alloc",
    "wall_clock": "lint-wall-clock",
}

# which roots report which effect kinds
_EVENT_KINDS = ("blocks", "transfers", "wall_clock")
_HOT_KINDS = ("allocates", "transfers")

_SUBPROCESS_CALLS = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
}


def _canonical(module, text: str) -> str:
    """Canonicalize a call target's head through the module's import
    aliases: `t.sleep` → `time.sleep`, `sleep` (from time import
    sleep) → `time.sleep`, `dt.datetime.now` → `datetime.datetime.now`.
    """
    head, sep, rest = text.partition(".")
    if head in module.imports:
        base = module.imports[head]
        return f"{base}.{rest}" if sep else base
    entry = module.from_imports.get(head)
    if entry is not None:
        base = f"{entry[0]}.{entry[1]}" if entry[0] else entry[1]
        return f"{base}.{rest}" if sep else base
    return text


def _direct_effects(module, info):
    """Yield (kind, lineno, detail) for every direct effect site in
    the function's own body (nested defs are their own nodes)."""
    from .callgraph import _own_nodes
    for node in _own_nodes(info.node):
        if not isinstance(node, ast.Call):
            continue
        tail = _func_tail(node.func)
        text = ast.unparse(node.func)
        canonical = _canonical(module, text)
        if canonical == "time.sleep":
            yield ("blocks", node.lineno, "time.sleep()")
        elif tail in _BLOCKING_ATTRS:
            yield ("blocks", node.lineno,
                   f".{tail}() — {_BLOCKING_ATTRS[tail]}")
        elif canonical in _SUBPROCESS_CALLS:
            detail = ("spawns a subprocess (fork/exec on this thread)"
                      if canonical == "subprocess.Popen"
                      else "spawns and waits on a subprocess")
            yield ("blocks", node.lineno, f"{canonical}() — {detail}")
        elif canonical in ("select.select",
                           "socket.create_connection"):
            yield ("blocks", node.lineno,
                   f"{canonical}() blocks on I/O readiness")
        elif isinstance(node.func, ast.Name) and \
                node.func.id == "open":
            yield ("blocks", node.lineno,
                   "open() synchronous file I/O")
        if canonical in ("jax.device_get", "jax.device_put"):
            yield ("transfers", node.lineno,
                   f"{canonical}() device/host transfer")
        elif tail in _TRANSFER_TAILS and node.args and \
                text.rpartition(".")[0] in _TRANSFER_MODULES:
            arg_src = ast.unparse(node.args[0])
            if any(token in arg_src for token in _POOL_ROW_TOKENS):
                yield ("transfers", node.lineno,
                       f"{text}() copies KV pool-block rows")
        if tail in _ALLOC_TAILS and \
                text.rpartition(".")[0] in _ALLOC_MODULES:
            yield ("allocates", node.lineno,
                   f"{text}() allocates a fresh array")
        if canonical in _WALL_CLOCK_CALLS:
            yield ("wall_clock", node.lineno,
                   f"{text}() reads the wall-epoch clock")


def _lock_name(info, text: str) -> str:
    """Lock identity for the static order graph: `self._x` qualified
    by the owning class so same-named locks on different classes stay
    distinct; everything else qualified by module."""
    if text.startswith("self.") and info.cls is not None:
        return f"{info.cls}.{text[5:]}"
    return f"{info.module}:{text}"


class EffectAnalysis:
    """Build → propagate → report.  Construct with a PackageGraph (or
    use effect_findings() for the one-shot path)."""

    def __init__(self, graph: PackageGraph,
                 waiver_log: WaiverLog | None = None):
        self.graph = graph
        self.waiver_log = waiver_log
        # function key -> {kind: witness}; witness is
        # ("leaf", lineno, detail) | ("call", lineno, callee_key)
        self.effects: dict[str, dict] = {}
        # function key -> {lock name: witness} (same witness shapes)
        self.acquires: dict[str, dict] = {}
        # (outer lock, function key, body call-site lineno, callee)
        self._held_calls: list = []
        # (lock_a, lock_b, "path:line") direct same-function edges
        self._direct_edges: set = set()

    # -- stage 1: direct effects ------------------------------------------
    def _waived(self, module, rule: str, lineno: int) -> bool:
        waived_at = module.waivers.match(rule, lineno)
        if waived_at is not None:
            if self.waiver_log is not None:
                self.waiver_log.mark_used(module.path, waived_at)
            return True
        return False

    def _collect_direct(self) -> None:
        for info in self.graph.functions.values():
            module = self.graph.modules[info.module]
            slots = self.effects.setdefault(info.key, {})
            for kind, lineno, detail in _direct_effects(module, info):
                if kind in slots:
                    continue
                if self._waived(module, EFFECT_RULES[kind], lineno):
                    continue    # waiver kills the effect at its source
                slots[kind] = ("leaf", lineno, detail)
            self._collect_locks(module, info)

    def _collect_locks(self, module, info) -> None:
        """Acquire sites and the ordering relation: a with-lock body's
        direct acquires and call sites (edges to the callee's
        transitive acquires resolve after propagation)."""
        from .callgraph import _own_nodes
        held: list = []     # stack of lock names for nested withs

        def walk(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.With):
                names = []
                for item in node.items:
                    if not _mentions_lock(item.context_expr):
                        continue
                    text = ast.unparse(item.context_expr)
                    # strip the .acquire_timeout()/() call suffix so
                    # `with self._lock:` and `with self._lock.held():`
                    # name the same lock
                    text = text.split("(")[0]
                    names.append(_lock_name(info, text))
                for name in names:
                    slot = self.acquires.setdefault(info.key, {})
                    slot.setdefault(name,
                                    ("leaf", node.lineno, name))
                    for outer in held:
                        if outer != name:
                            self._direct_edges.add(
                                (outer, name,
                                 f"{info.path}:{node.lineno}"))
                held.extend(names)
                for child in node.body:
                    walk(child)
                del held[len(held) - len(names):]
                return
            if isinstance(node, ast.Call) and held:
                callee = self._callee_at(info, node.lineno)
                if callee is not None:
                    for outer in held:
                        self._held_calls.append(
                            (outer, info.key, node.lineno, callee))
            for child in ast.iter_child_nodes(node):
                walk(child)

        for child in ast.iter_child_nodes(info.node):
            walk(child)

    def _callee_at(self, info, lineno: int):
        for site in info.calls:
            if site.lineno == lineno:
                return site.callee
        return None

    # -- stage 2: fixpoint propagation ------------------------------------
    def _propagate(self) -> None:
        functions = self.graph.functions
        changed = True
        while changed:
            changed = False
            for info in functions.values():
                module = self.graph.modules[info.module]
                slots = self.effects.setdefault(info.key, {})
                lock_slot = self.acquires.setdefault(info.key, {})
                for site in info.calls:
                    callee_effects = self.effects.get(site.callee)
                    if callee_effects:
                        for kind in callee_effects:
                            if kind in slots:
                                continue
                            if self._waived(module, EFFECT_RULES[kind],
                                            site.lineno):
                                continue    # waiver severs this edge
                            slots[kind] = ("call", site.lineno,
                                           site.callee)
                            changed = True
                    callee_locks = self.acquires.get(site.callee)
                    if callee_locks:
                        for name in callee_locks:
                            if name not in lock_slot:
                                lock_slot[name] = ("call", site.lineno,
                                                   site.callee)
                                changed = True

    def run(self) -> "EffectAnalysis":
        self._collect_direct()
        self._propagate()
        return self

    # -- provenance --------------------------------------------------------
    def chain(self, key: str, kind: str) -> list:
        """Root-to-leaf provenance frames, 'path:line qualname' per
        hop, the leaf frame carrying the offending call's detail."""
        frames: list = []
        current = key
        for _ in range(len(self.graph.functions) + 1):
            info = self.graph.functions[current]
            witness = self.effects[current][kind]
            if witness[0] == "leaf":
                frames.append(f"{info.path}:{witness[1]} "
                              f"{info.qualname} → {witness[2]}")
                break
            frames.append(f"{info.path}:{witness[1]} {info.qualname}")
            current = witness[2]
        return frames

    def lock_order_edges(self) -> set:
        """Static (lock_a, lock_b, provenance) edges: direct nesting
        plus with-lock bodies calling into transitive acquirers."""
        edges = set(self._direct_edges)
        for outer, func_key, lineno, callee in self._held_calls:
            for name in self.acquires.get(callee, {}):
                if name != outer:
                    info = self.graph.functions[func_key]
                    edges.add((outer, name,
                               f"{info.path}:{lineno}"))
        return edges

    def _lock_cycle_findings(self) -> list:
        adjacency: dict[str, dict] = {}
        for a, b, where in sorted(self.lock_order_edges()):
            adjacency.setdefault(a, {}).setdefault(b, where)
        findings = []
        seen_cycles = set()
        for start in sorted(adjacency):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adjacency.get(node, {})):
                    if nxt == start and len(path) > 1:
                        cycle = frozenset(path)
                        if cycle in seen_cycles:
                            continue
                        seen_cycles.add(cycle)
                        hops = path + [start]
                        provenance = "; ".join(
                            f"{a}→{b} at "
                            f"{adjacency[a][b]}"
                            for a, b in zip(hops, hops[1:]))
                        findings.append(Finding(
                            "lint-lock-order", WARNING,
                            adjacency[path[-1]][start].rsplit(
                                ":", 1)[0],
                            int(adjacency[path[-1]][start].rsplit(
                                ":", 1)[1]),
                            f"static lock-order cycle "
                            f"{' → '.join(hops)}: {provenance} — "
                            f"acquire in one global order or the "
                            f"runtime detector will fire under load"))
                    elif nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))
        return findings

    # -- stage 3: findings -------------------------------------------------
    def findings(self) -> list:
        results: list = []
        roots = [(key, "event", _EVENT_KINDS)
                 for key in sorted(self.graph.event_roots)]
        roots += [(key, "hot", _HOT_KINDS)
                  for key in sorted(self.graph.hot_roots)]
        seen = set()
        for key, root_kind, kinds in roots:
            info = self.graph.functions.get(key)
            if info is None or _is_test_path(info.path):
                continue
            module = self.graph.modules[info.module]
            for kind in kinds:
                witness = self.effects.get(key, {}).get(kind)
                if witness is None or witness[0] != "call":
                    continue    # depth 0 is the syntactic rule's job
                rule = EFFECT_RULES[kind]
                dedup = (rule, key, kind)
                if dedup in seen:
                    continue
                seen.add(dedup)
                # a waiver on the root's def line silences the root
                if self._waived(module, rule, info.lineno):
                    continue
                frames = self.chain(key, kind)
                leaf = frames[-1].rsplit("→", 1)[-1].strip()
                context = "event-loop context" if root_kind == "event" \
                    else "hot path"
                results.append(Finding(
                    rule, ERROR, info.path, witness[1],
                    f"{context} {info.qualname!r} transitively "
                    f"reaches {leaf} ({len(frames) - 1} call(s) "
                    f"deep): every frame below may carry a "
                    f"`graft: disable={rule}` waiver",
                    chain=tuple(frames)))
        results.extend(self._lock_cycle_findings())
        return results


def effect_findings(paths, root=None,
                    waiver_log: WaiverLog | None = None,
                    graph: PackageGraph | None = None) -> list:
    """One-shot: build the call graph over `paths`, run the effect
    analysis, return interprocedural findings."""
    if graph is None:
        graph = build_graph(paths, root)
    return EffectAnalysis(graph, waiver_log).run().findings()
