# Findings baseline (ISSUE 18): land strict-on-new without a big-bang
# cleanup.
#
# A baseline is a committed JSON file mapping finding FINGERPRINTS to
# occurrence counts.  `--baseline FILE` subtracts baselined findings
# from the gate: pre-existing debt stays visible in the file (one
# reviewable line per acknowledged finding) while any NEW finding —
# or any extra occurrence of a baselined one — still fails CI.  A
# baseline entry that no longer matches anything becomes a
# `baseline-stale` warning, so paid-down debt is removed from the file
# instead of rotting (`--update-baseline` regenerates it).
#
# Fingerprints are `rule|relative-path|message` with every `:<line>`
# inside the message normalized to `:*`, so a pure line-number shift
# (code added above a finding) neither breaks the suppression nor
# lets a second, genuinely new occurrence hide.  Provenance chains are
# NOT part of the fingerprint — interprocedural call routes shift with
# any refactor; the root finding's identity is rule + file + message.

from __future__ import annotations

import json
import re
from pathlib import Path

from .findings import Finding, WARNING

__all__ = [
    "BASELINE_VERSION", "fingerprint", "load_baseline",
    "apply_baseline", "write_baseline",
]

BASELINE_VERSION = 1
_LINE_RE = re.compile(r":\d+")


def fingerprint(finding: Finding, root: Path) -> str:
    try:
        rel = str(Path(finding.path).resolve()
                  .relative_to(Path(root).resolve()))
    except (ValueError, OSError):
        rel = finding.path
    message = _LINE_RE.sub(":*", finding.message)
    return f"{finding.rule}|{rel}|{message}"


def load_baseline(path: Path) -> dict:
    """{fingerprint: count} from a baseline file.  Raises OSError /
    ValueError on unreadable or malformed input — a broken baseline
    must fail the gate, not silently suppress nothing (or everything)."""
    data = json.loads(Path(path).read_text())
    if not isinstance(data, dict) or \
            not isinstance(data.get("entries"), dict):
        raise ValueError(f"baseline {path}: want "
                         f'{{"version": .., "entries": {{..}}}}')
    entries = {}
    for key, count in data["entries"].items():
        if not isinstance(key, str) or not isinstance(count, int) \
                or count < 1:
            raise ValueError(f"baseline {path}: bad entry {key!r}")
        entries[key] = count
    return entries


def apply_baseline(findings, entries: dict, root: Path,
                   baseline_path: Path) -> list:
    """Subtract baselined findings; returns the survivors PLUS one
    `baseline-stale` warning per entry that matched fewer findings
    than its count (the debt was paid down — regenerate the file)."""
    remaining = dict(entries)
    survivors = []
    for finding in findings:
        key = fingerprint(finding, root)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
        else:
            survivors.append(finding)
    for key in sorted(key for key, count in remaining.items()
                      if count > 0):
        rule = key.split("|", 1)[0]
        survivors.append(Finding(
            "baseline-stale", WARNING, str(baseline_path), 0,
            f"baseline entry no longer matches any finding "
            f"(rule {rule}, {remaining[key]} unmatched): regenerate "
            f"with --update-baseline", ))
    return survivors


def write_baseline(path: Path, findings, root: Path) -> Path:
    entries: dict = {}
    for finding in findings:
        key = fingerprint(finding, root)
        entries[key] = entries.get(key, 0) + 1
    document = {
        "version": BASELINE_VERSION,
        "entries": {key: entries[key] for key in sorted(entries)},
    }
    path = Path(path)
    path.write_text(json.dumps(document, indent=2) + "\n")
    return path
