# graft-check: static analysis for the pipeline framework.
#
# Three layers, one CLI (`python -m aiko_services_tpu.analysis`):
#   * graph_check — contract-check a PipelineDefinition without
#     instantiating elements (dataflow, name mappings, dtype/shape/codec
#     contracts, remote-hop wire codec legality);
#   * lint — AST rules over package and user element files (blocking
#     calls in event-loop handlers, raw locks, validation asserts,
#     publish-under-lock, jit-in-frame);
#   * the runtime lock-order detector lives in utils/lock.py (opt-in via
#     AIKO_LOCK_CHECK=1) — the dynamic complement to these static layers.
#
# Findings are structured (rule id, severity, file:line) so CI gates on
# them; see README "Static analysis (graft-check)" for the rule catalog.

from .findings import (                                     # noqa: F401
    ERROR, WARNING, INFO, Finding, format_findings, has_errors,
)
from .contracts import (                                    # noqa: F401
    Alt, ContractError, compatible, parse_contract,
)
from .graph_check import (                                  # noqa: F401
    check_definition, check_pipeline_file,
)
from .lint import (                                         # noqa: F401
    LINT_RULES, lint_file, lint_paths, lint_source,
)
from .cli import main, self_check_findings                  # noqa: F401

__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "format_findings",
    "has_errors", "Alt", "ContractError", "compatible", "parse_contract",
    "check_definition", "check_pipeline_file",
    "LINT_RULES", "lint_file", "lint_paths", "lint_source",
    "main", "self_check_findings",
]
