# graft-check: static analysis for the pipeline framework.
#
# Five layers, one CLI (`python -m aiko_services_tpu.analysis`):
#   * graph_check — contract-check a PipelineDefinition without
#     instantiating elements (dataflow, name mappings, dtype/shape/codec
#     contracts, remote-hop wire codec legality);
#   * lint — AST rules over package and user element files (blocking
#     calls in event-loop handlers, raw locks, validation asserts,
#     publish-under-lock, jit-in-frame);
#   * effects — whole-package call graph (callgraph.py) with per-function
#     effect sets propagated transitively, so a blocking/allocating/
#     transferring leaf is reported at every event-loop or hot-path root
#     that can reach it, with the root-to-leaf provenance chain;
#   * drift — metric families consumed vs created (lint-metric-drift)
#     and the wire envelope vs the committed wire_schema.lock
#     (lint-wire-schema);
#   * baseline — committed findings fingerprints so `--strict` can gate
#     on NEW findings without a big-bang cleanup of acknowledged debt.
# The runtime lock-order detector lives in utils/lock.py (opt-in via
# AIKO_LOCK_CHECK=1) — the dynamic complement to these static layers.
#
# Findings are structured (rule id, severity, file:line, provenance
# chain) so CI gates on them; see README "Static analysis (graft-check)"
# for the rule catalog.

from .findings import (                                     # noqa: F401
    ERROR, WARNING, INFO, Finding, format_findings, has_errors,
)
from .contracts import (                                    # noqa: F401
    Alt, ContractError, compatible, parse_contract,
)
from .graph_check import (                                  # noqa: F401
    check_definition, check_pipeline_file,
)
from .lint import (                                         # noqa: F401
    LINT_RULES, WaiverLog, lint_file, lint_paths, lint_source,
    rule_catalog,
)
from .callgraph import build_graph, iter_python_files       # noqa: F401
from .effects import EFFECT_RULES, effect_findings          # noqa: F401
from .drift import (                                        # noqa: F401
    METRIC_DRIFT_ALLOWLIST, metric_drift_findings,
    wire_schema_findings, wire_schema_snapshot, write_wire_lock,
)
from .baseline import (                                     # noqa: F401
    apply_baseline, fingerprint, load_baseline, write_baseline,
)
from .cli import main, self_check_findings                  # noqa: F401

__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "format_findings",
    "has_errors", "Alt", "ContractError", "compatible", "parse_contract",
    "check_definition", "check_pipeline_file",
    "LINT_RULES", "WaiverLog", "lint_file", "lint_paths", "lint_source",
    "rule_catalog", "build_graph", "iter_python_files",
    "EFFECT_RULES", "effect_findings",
    "METRIC_DRIFT_ALLOWLIST", "metric_drift_findings",
    "wire_schema_findings", "wire_schema_snapshot", "write_wire_lock",
    "apply_baseline", "fingerprint", "load_baseline", "write_baseline",
    "main", "self_check_findings",
]
