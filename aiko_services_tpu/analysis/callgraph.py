# Whole-package call graph for interprocedural analysis (ISSUE 18).
#
# Purely static, like the lint rules: parse every module once, index
# functions/methods/classes, then resolve call sites with a ladder of
# heuristics ordered strictest-first:
#
#   1. local name → function or nested def in the same scope/module
#   2. from-import → symbol in the imported package module
#   3. module alias prefix (`wire.encode`, `aiko_services_tpu.x.f`)
#   4. `self.` / `cls.` receiver → method on the enclosing class,
#      walking resolved base classes
#   5. typed receiver → a local `x = ClassName(...)` assignment or a
#      `self.attr = ClassName(...)` attribute-type learned from any
#      method of the class
#   6. unique-bare-name fallback → an attribute call whose method name
#      exists exactly ONCE package-wide and is not a ubiquitous verb
#      (`run`, `get`, `close`, ...) — the stoplist keeps this from
#      inventing edges through dict.get or file.close
#
# `functools.partial(f, ...)` contributes an edge to `f` (partials are
# this codebase's handler/callback currency).  `add_*_handler(f)`
# registrations do NOT create an edge from the registering function —
# registering a handler is not calling it — but they DO mark `f` as an
# event-loop ROOT, exactly like the frame methods, which is what the
# effect propagation needs.  Nested defs and lambdas are their own
# nodes reached only by explicit calls, so a nested thread target's
# blocking calls never leak into its parent (mirroring the lint
# scanner's no-descent rule).

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .lint import (_FRAME_METHODS, _HANDLER_REGISTRARS, _HOT_MARKER,
                   WaiverIndex, _func_tail)

__all__ = ["CallSite", "FunctionInfo", "ModuleInfo", "PackageGraph",
           "build_graph", "iter_python_files"]

# method names too common for the unique-bare-name fallback: a single
# package-wide definition of `close` does not mean every `x.close()`
# is it (file objects, sockets, and queues all spell it the same way)
_COMMON_NAMES = {
    "run", "start", "stop", "close", "open", "get", "put", "set",
    "add", "remove", "update", "read", "write", "send", "recv",
    "publish", "subscribe", "append", "appendleft", "pop", "popleft",
    "clear", "join", "wait", "notify", "notify_all", "acquire",
    "release", "submit", "process", "handle", "emit", "flush",
    "reset", "terminate", "encode", "decode", "parse", "render",
    "main", "copy", "items", "keys", "values", "setdefault", "extend",
    "insert", "index", "count", "sort", "sorted", "format", "strip",
    "split", "lower", "upper", "replace", "match", "search", "group",
    "load", "loads", "dump", "dumps", "save", "map", "collect",
    "exists", "mkdir", "resolve",
    "name", "value", "result", "cancel", "stat", "snapshot", "step",
    "tick", "poll", "drain", "connect", "bind", "accept", "fileno",
    "shutdown", "info", "warning", "error", "debug", "exception",
}


@dataclass
class CallSite:
    lineno: int
    text: str                   # call-target source, for diagnostics
    callee: str | None          # resolved function key, or None
    kind: str = "call"          # call | partial


@dataclass
class FunctionInfo:
    key: str                    # "module_key::Qual.name"
    module: str                 # owning module key
    path: str
    name: str                   # bare name
    qualname: str               # Class.method / outer.<locals>.inner
    lineno: int
    node: ast.AST = field(repr=False)
    cls: str | None = None      # owning class key, when a method
    calls: list = field(default_factory=list)


@dataclass
class ClassInfo:
    key: str                    # "module_key::ClassName"
    module: str
    name: str
    bases: list = field(default_factory=list)       # base source texts
    methods: dict = field(default_factory=dict)     # name -> func key
    attr_types: dict = field(default_factory=dict)  # attr -> class key


class ModuleInfo:
    """One parsed module: tree, waiver index, import maps, and its
    top-level symbol tables."""

    def __init__(self, key: str, path: Path, source: str,
                 tree: ast.AST, is_package: bool = False):
        self.key = key
        self.is_package = is_package
        self.path = str(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.waivers = WaiverIndex(source, tree)
        # alias -> dotted module name ("np" -> "numpy")
        self.imports: dict[str, str] = {}
        # local name -> (dotted module, original symbol name)
        self.from_imports: dict[str, tuple] = {}
        self.functions: dict[str, str] = {}   # top-level name -> key
        self.classes: dict[str, str] = {}     # class name -> class key

    def resolve_module_alias(self, dotted: str) -> str | None:
        """Map a call-target prefix through this module's import
        aliases: 'wire' -> 'aiko_services_tpu.transport.wire'."""
        if dotted in self.imports:
            return self.imports[dotted]
        head, sep, rest = dotted.partition(".")
        if sep and head in self.imports:
            return f"{self.imports[head]}.{rest}"
        entry = self.from_imports.get(head)
        if entry is not None:
            # `from aiko_services_tpu import transport` style: the
            # imported symbol may itself be a module
            dotted_head = f"{entry[0]}.{entry[1]}"
            return f"{dotted_head}.{rest}" if sep else dotted_head
        return None


def iter_python_files(paths):
    """The analysis file set: files and/or directories (recursive over
    *.py, skipping __pycache__), deduplicated, in sorted order."""
    seen = set()
    for entry in paths:
        path = Path(entry)
        if path.is_dir():
            candidates = sorted(path.rglob("*.py"))
        elif path.suffix == ".py" and path.exists():
            candidates = [path]
        else:
            candidates = []
        for file_path in candidates:
            if "__pycache__" in file_path.parts:
                continue
            resolved = file_path.resolve()
            if resolved not in seen:
                seen.add(resolved)
                yield file_path


def _module_key(path: Path, root: Path) -> str:
    """Dotted module name relative to the repo root — the name the
    import maps resolve against ('aiko_services_tpu.transport.wire',
    'bench', 'scripts.chaos_soak')."""
    try:
        relative = path.resolve().relative_to(root.resolve())
    except ValueError:
        relative = Path(path.name)
    parts = list(relative.parts)
    if parts[-1] == "__init__.py":
        parts = parts[:-1] or [path.parent.name]
    else:
        parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") \
            else parts[-1]
    return ".".join(parts)


def _resolve_import_from(module: ModuleInfo,
                         node: ast.ImportFrom) -> str:
    """Absolute dotted module for a (possibly relative) from-import."""
    if not node.level:
        return node.module or ""
    parts = module.key.split(".")
    # level 1 = the current package: a plain module drops its own leaf,
    # a package __init__ IS its package and drops nothing
    drop = node.level - (1 if module.is_package else 0)
    base = parts[:len(parts) - drop] if drop <= len(parts) else []
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base)


class PackageGraph:
    def __init__(self, root: Path):
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.event_roots: set[str] = set()
        self.hot_roots: set[str] = set()
        # bare method/function name -> [keys] for the unique fallback
        self._bare: dict[str, list] = {}

    # -- symbol lookup -----------------------------------------------------
    def module_function(self, module_key: str, name: str) -> str | None:
        module = self.modules.get(module_key)
        if module is None:
            return None
        if name in module.functions:
            return module.functions[name]
        class_key = module.classes.get(name)
        if class_key is not None:
            # calling a class = running its __init__
            return self.classes[class_key].methods.get("__init__")
        entry = module.from_imports.get(name)
        if entry is not None and entry[0] in self.modules:
            return self.module_function(entry[0], entry[1])
        return None

    def module_class(self, module_key: str, name: str) -> str | None:
        module = self.modules.get(module_key)
        if module is None:
            return None
        if name in module.classes:
            return module.classes[name]
        entry = module.from_imports.get(name)
        if entry is not None and entry[0] in self.modules:
            return self.module_class(entry[0], entry[1])
        head, sep, tail = name.partition(".")
        if sep:
            target = module.resolve_module_alias(head)
            if target is not None and target in self.modules:
                return self.module_class(target, tail)
        return None

    def method_on(self, class_key: str | None, name: str,
                  depth: int = 0) -> str | None:
        """Method lookup walking resolved base classes (depth-capped:
        base texts are source strings, cycles are conceivable)."""
        if class_key is None or depth > 5:
            return None
        info = self.classes.get(class_key)
        if info is None:
            return None
        if name in info.methods:
            return info.methods[name]
        for base_text in info.bases:
            base_key = self.module_class(info.module, base_text)
            if base_key is not None and base_key != class_key:
                found = self.method_on(base_key, name, depth + 1)
                if found is not None:
                    return found
        return None

    def unique_bare(self, name: str) -> str | None:
        if name in _COMMON_NAMES or name.startswith("__"):
            return None
        keys = self._bare.get(name)
        return keys[0] if keys is not None and len(keys) == 1 else None


# ---------------------------------------------------------------------------
# graph construction


def _hot_marked(module: ModuleInfo, node) -> bool:
    for line_number in (node.lineno, node.lineno - 1):
        if 1 <= line_number <= len(module.lines) and \
                _HOT_MARKER in module.lines[line_number - 1]:
            return True
    return False


def _index_module(graph: PackageGraph, module: ModuleInfo) -> None:
    """First pass: imports, classes/methods, functions (incl. nested),
    class attribute types, hot markers."""
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for entry in node.names:
                module.imports[entry.asname
                               or entry.name.partition(".")[0]] = \
                    entry.name if entry.asname else \
                    entry.name.partition(".")[0]
                if entry.asname:
                    module.imports[entry.asname] = entry.name
        elif isinstance(node, ast.ImportFrom):
            source_module = _resolve_import_from(module, node)
            for entry in node.names:
                if entry.name == "*":
                    continue
                module.from_imports[entry.asname or entry.name] = \
                    (source_module, entry.name)

    def add_function(node, qualname, cls_key=None):
        key = f"{module.key}::{qualname}"
        info = FunctionInfo(key=key, module=module.key,
                            path=module.path, name=node.name,
                            qualname=qualname, lineno=node.lineno,
                            node=node, cls=cls_key)
        graph.functions[key] = info
        graph._bare.setdefault(node.name, []).append(key)
        if node.name in _FRAME_METHODS:
            graph.event_roots.add(key)
        if _hot_marked(module, node):
            graph.hot_roots.add(key)
        return key

    def walk_body(body, prefix, cls_key=None):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                key = add_function(node, qual, cls_key)
                if cls_key is not None:
                    graph.classes[cls_key].methods[node.name] = key
                walk_body(node.body, f"{qual}.<locals>.", None)
            elif isinstance(node, ast.ClassDef):
                class_key = f"{module.key}::{prefix}{node.name}"
                graph.classes[class_key] = ClassInfo(
                    key=class_key, module=module.key, name=node.name,
                    bases=[ast.unparse(base) for base in node.bases])
                if not prefix:
                    module.classes[node.name] = class_key
                walk_body(node.body, f"{prefix}{node.name}.",
                          class_key)
            elif isinstance(node, (ast.If, ast.Try, ast.With)):
                # conditional/guarded top-level defs still count
                walk_body(getattr(node, "body", []), prefix, cls_key)
                for handler in getattr(node, "handlers", []):
                    walk_body(handler.body, prefix, cls_key)
                walk_body(getattr(node, "orelse", []), prefix, cls_key)
                walk_body(getattr(node, "finalbody", []), prefix,
                          cls_key)

    walk_body(module.tree.body, "")
    for key, info in graph.functions.items():
        if info.module == module.key and "." not in info.qualname:
            module.functions[info.name] = key


def _learn_attr_types(graph: PackageGraph, module: ModuleInfo) -> None:
    """`self.attr = ClassName(...)` in any method teaches the class
    that `self.attr` is a ClassName — the receiver-type heuristic."""
    for info in list(graph.functions.values()):
        if info.module != module.key or info.cls is None:
            continue
        cls = graph.classes[info.cls]
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            ctor = ast.unparse(node.value.func)
            class_key = graph.module_class(module.key, ctor)
            if class_key is None:
                continue
            for target in node.targets:
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    cls.attr_types.setdefault(target.attr, class_key)


def _own_nodes(func_node):
    """Nodes of a function body excluding nested function/lambda
    bodies — those are their own graph nodes."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _resolve_callable_ref(graph, module, info, node,
                          local_types, nested):
    """Resolve a reference to a callable (handler / partial argument /
    call target) to a function key, or None."""
    if isinstance(node, ast.Name):
        if node.id in nested:
            return nested[node.id]
        return graph.module_function(module.key, node.id)
    if not isinstance(node, ast.Attribute):
        return None
    name = node.attr
    receiver = node.value
    receiver_text = ast.unparse(receiver)
    # self./cls. → enclosing class (and its bases)
    if receiver_text in ("self", "cls") and info.cls is not None:
        return graph.method_on(info.cls, name)
    # module alias prefix: wire.encode, functools.partial, pkg.mod.f —
    # and a DEFINITE bail for external modules (np.save, jax.tree.map):
    # a known-foreign receiver must never reach the unique-bare guess
    head = receiver_text.partition(".")[0]
    if head in module.imports:
        target_module = module.resolve_module_alias(receiver_text)
        if target_module is not None and target_module in graph.modules:
            return graph.module_function(target_module, name)
        return None
    target_module = module.resolve_module_alias(receiver_text)
    if target_module is not None and target_module in graph.modules:
        return graph.module_function(target_module, name)
    # self.attr receiver with a learned attribute type
    if isinstance(receiver, ast.Attribute) and \
            isinstance(receiver.value, ast.Name) and \
            receiver.value.id == "self" and info.cls is not None:
        attr_class = graph.classes[info.cls].attr_types.get(
            receiver.attr)
        if attr_class is not None:
            found = graph.method_on(attr_class, name)
            if found is not None:
                return found
    # local var with an inferred constructor type
    if isinstance(receiver, ast.Name):
        var_class = local_types.get(receiver.id)
        if var_class is not None:
            found = graph.method_on(var_class, name)
            if found is not None:
                return found
    # ClassName.method as an unbound reference
    class_key = graph.module_class(module.key, receiver_text)
    if class_key is not None:
        found = graph.method_on(class_key, name)
        if found is not None:
            return found
    return graph.unique_bare(name)


def _extract_calls(graph: PackageGraph, module: ModuleInfo,
                   info: FunctionInfo) -> None:
    """Second pass per function: local type inference, then one
    CallSite per own-body call, partial edge, and handler-root mark."""
    # nested-def keys follow _index_module's qualname scheme
    nested = {
        child.name:
            f"{module.key}::{info.qualname}.<locals>.{child.name}"
        for child in ast.iter_child_nodes(info.node)
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))}
    local_types: dict[str, str] = {}
    for node in _own_nodes(info.node):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            class_key = graph.module_class(
                module.key, ast.unparse(node.value.func))
            if class_key is not None:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        local_types[target.id] = class_key
    for node in _own_nodes(info.node):
        if not isinstance(node, ast.Call):
            continue
        tail = _func_tail(node.func)
        text = ast.unparse(node.func)
        # handler registration: marks the target an event ROOT, not an
        # edge — registering is not calling
        if tail in _HANDLER_REGISTRARS and node.args:
            target_key = _resolve_callable_ref(
                graph, module, info, node.args[0], local_types, nested)
            if target_key is not None:
                graph.event_roots.add(target_key)
            continue
        # functools.partial(f, ...): edge to f — partials are the
        # callback currency, the partial's caller will invoke f
        if tail == "partial" and node.args and \
                text in ("functools.partial", "partial"):
            target_key = _resolve_callable_ref(
                graph, module, info, node.args[0], local_types, nested)
            if target_key is not None:
                info.calls.append(CallSite(
                    lineno=node.lineno,
                    text=ast.unparse(node.args[0]),
                    callee=target_key, kind="partial"))
            continue
        callee = _resolve_callable_ref(
            graph, module, info, node.func, local_types, nested)
        if callee is not None and callee != info.key:
            info.calls.append(CallSite(lineno=node.lineno, text=text,
                                       callee=callee))


def build_graph(paths, root=None) -> PackageGraph:
    """Parse every python file under `paths` and return the resolved
    package call graph.  `root` anchors dotted module names (defaults
    to the repo root: the analysis package's grandparent)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    graph = PackageGraph(Path(root))
    for file_path in iter_python_files(paths):
        try:
            source = file_path.read_text(encoding="utf-8")
            tree = ast.parse(source)
        except (OSError, UnicodeDecodeError, SyntaxError):
            continue        # lint reports parse failures; skip here
        key = _module_key(file_path, graph.root)
        graph.modules[key] = ModuleInfo(
            key, file_path, source, tree,
            is_package=file_path.name == "__init__.py")
    for module in graph.modules.values():
        _index_module(graph, module)
    for module in graph.modules.values():
        _learn_attr_types(graph, module)
    for info in graph.functions.values():
        _extract_calls(graph, graph.modules[info.module], info)
    return graph
