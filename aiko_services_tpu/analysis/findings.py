# Structured findings: the one record every graft-check layer emits.
#
# A finding is machine-gateable (rule id + severity) and human-locatable
# (file:line + message).  CI gates on error-severity findings; warnings
# surface design smells (dead outputs, unreachable elements) without
# failing the build.  Interprocedural findings (effects.py) additionally
# carry a provenance `chain`: the root-to-leaf call path, one
# "path:line qualname" frame per hop, so a finding at an event-handler
# root names the exact helper route to the offending leaf call.

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

__all__ = ["Finding", "ERROR", "WARNING", "INFO", "has_errors",
           "format_findings"]

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclass(frozen=True)
class Finding:
    rule: str               # stable rule id, e.g. "graph-missing-input"
    severity: str           # error | warning | info
    path: str               # file pathname or definition name
    line: int               # 1-based; 0 = whole-file / whole-definition
    message: str
    # provenance frames root→leaf ("path:line qualname"); None for
    # syntactic findings, so pre-chain consumers see an unchanged record
    chain: tuple = field(default=None, compare=False)

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def __str__(self) -> str:
        text = f"{self.severity:<7} {self.rule:<24} {self.location}: " \
               f"{self.message}"
        if self.chain:
            text += "".join(f"\n        via {frame}"
                            for frame in self.chain)
        return text


def has_errors(findings) -> bool:
    return any(f.severity == ERROR for f in findings)


def format_findings(findings, fmt: str = "text") -> str:
    """Render findings for the CLI: stable order (severity, path, line)."""
    ordered = sorted(findings,
                     key=lambda f: (_SEVERITY_ORDER.get(f.severity, 3),
                                    f.path, f.line, f.rule))
    if fmt == "json":
        return json.dumps([asdict(f) for f in ordered], indent=2)
    return "\n".join(str(f) for f in ordered)
