# Drift checkers: metric names and the wire envelope (ISSUE 18).
#
# Ten PRs of growth created two unchecked surfaces:
#
#   * ~80 metric/bench family names consumed by bench.py, scripts/,
#     the autoscaler, and the dashboard with no cross-check against
#     their registry creation sites — a renamed
#     `serving_itl_seconds` ships silently and every consumer reads 0
#     forever.  `lint-metric-drift` cross-references the two sides.
#
#   * a wire envelope whose field list (buffer marker, trace marker,
#     tenant marker, the PR 17 ninth "chunk" param, codec tables, hop
#     entry arity) is kept compatible only by convention.
#     `lint-wire-schema` snapshots the declared constants from
#     transport/wire.py and compares them against a COMMITTED lock
#     file (analysis/wire_schema.lock), so any envelope change is an
#     explicit two-sided diff: change the constant AND regenerate the
#     lock (`python -m aiko_services_tpu.analysis --update-wire-lock`).
#
# Both checkers emit the same Finding records as the syntactic lint,
# honor `# graft: disable=<rule>` waivers at the reported line, and run
# from the CLI's --self-check pass.

from __future__ import annotations

import ast
import json
import re
from pathlib import Path

from .findings import ERROR, Finding, WARNING
from .lint import WaiverIndex, WaiverLog, _func_tail, _is_test_path

__all__ = [
    "METRIC_DRIFT_ALLOWLIST", "metric_drift_findings",
    "wire_schema_snapshot", "wire_schema_findings", "write_wire_lock",
    "WIRE_LOCK_NAME",
]

WIRE_LOCK_NAME = "wire_schema.lock"

# -- lint-metric-drift --------------------------------------------------------

# registry factory method tails: a call `<...registry...>.counter(
# "name", ...)` CREATES the family
_FACTORY_TAILS = {"counter", "gauge", "histogram", "sketch"}
# consumer method tails whose first string argument names a family:
# registry reads (value/series), the metrics-store selector API
# (observe/series.py), and the autoscaler's signal helpers
_CONSUMER_TAILS = {
    "value", "series", "merged_sketch", "sketch_window",
    "selector_delta", "selector_exemplars", "selector_level",
    "_worst", "_merged_p95",
}
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{3,}$")

# Families consumed (or created) on one side only ON PURPOSE.  Keep
# this list justified: every entry is either a hardware counter whose
# creation site lands with the r06 TPU sweep, or an export-only gauge
# whose consumer is an external scraper, not this repo.
METRIC_DRIFT_ALLOWLIST = frozenset({
    # r06 placeholders: bench table columns already reserve these
    # hardware families; the TPU sweep adds the creation sites
    "tpu_duty_cycle_percent",
    "tpu_hbm_bytes_used",
})


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_pattern(node) -> str | None:
    """An f-string first argument becomes a match pattern: literal
    fragments kept, every interpolation matches one identifier run."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts = []
    for value in node.values:
        if isinstance(value, ast.Constant) and \
                isinstance(value.value, str):
            parts.append(re.escape(value.value))
        else:
            parts.append(r"[A-Za-z0-9_.\-]+")
    return "".join(parts)


def _receiver_text(func) -> str:
    if not isinstance(func, ast.Attribute):
        return ""
    try:
        return ast.unparse(func.value)
    except Exception:
        return ""


def _strip_selector(name: str) -> str:
    """'family{label=v}:p95' -> 'family' (store selector syntax)."""
    return name.split("{", 1)[0].split(":", 1)[0]


class _MetricScan(ast.NodeVisitor):
    """One file's creation and consumption sites."""

    def __init__(self, path: str, consumer: bool):
        self.path = path
        self.consumer = consumer
        self.created: list = []       # (name, lineno)
        self.patterns: list = []      # (regex, lineno) f-string creates
        self.consumed: list = []      # (name, lineno)

    def visit_Call(self, node: ast.Call) -> None:
        tail = _func_tail(node.func)
        receiver = _receiver_text(node.func)
        if tail in _FACTORY_TAILS and "registry" in receiver.lower() \
                and node.args:
            name = _const_str(node.args[0])
            if name is not None and _NAME_RE.match(name):
                self.created.append((name, node.lineno))
            else:
                pattern = _fstring_pattern(node.args[0])
                if pattern:
                    self.patterns.append((pattern, node.lineno))
        elif tail == "MirroredStats":
            for keyword in node.keywords:
                if keyword.arg == "metric":
                    name = _const_str(keyword.value)
                    if name:
                        self.created.append((name, node.lineno))
        elif self.consumer and tail in _CONSUMER_TAILS and node.args:
            name = _const_str(node.args[0])
            if name is not None:
                name = _strip_selector(name)
                if _NAME_RE.match(name):
                    self.consumed.append((name, node.lineno))
        elif self.consumer and tail == "add" and \
                "famil" in receiver.lower() and node.args:
            name = _const_str(node.args[0])
            if name is not None and _NAME_RE.match(name):
                self.consumed.append((name, node.lineno))
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # `family == "name"` / `family in ("a", "b")`: the journey and
        # snapshot mergers dispatch on family names this way
        if self.consumer:
            sides = [node.left, *node.comparators]
            texts = []
            for side in sides:
                try:
                    texts.append(ast.unparse(side))
                except Exception:
                    texts.append("")
            if any("family" in text or "name" == text
                   for text in texts):
                for side in sides:
                    for name in self._names_in(side):
                        self.consumed.append((name, node.lineno))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # tuples of family names assigned to *FAMILIES* / *SIGNAL*
        # constants (the autoscaler's signal list)
        if self.consumer:
            for target in node.targets:
                label = getattr(target, "id",
                                getattr(target, "attr", "")) or ""
                if "FAMILIES" in label or "SIGNAL" in label or \
                        "famil" in label:
                    for name in self._names_in(node.value):
                        self.consumed.append((name, node.lineno))
        self.generic_visit(node)

    @staticmethod
    def _names_in(node) -> list:
        names = []
        value = _const_str(node)
        if value is not None and _NAME_RE.match(value):
            names.append(value)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for element in node.elts:
                value = _const_str(element)
                if value is not None and _NAME_RE.match(value):
                    names.append(value)
        return names


def _is_consumer_path(path: Path, root: Path) -> bool:
    """Files whose metric-name strings count as CONSUMPTION: bench,
    scripts/, tools/, the autoscaler, the dashboard, and observe/
    (journey merging, export)."""
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return False
    parts = rel.parts
    return (
        rel.name == "bench.py"
        or parts[0] in ("scripts", "tools")
        or rel.name in ("autoscaler.py", "dashboard.py",
                        "dashboard_plugins.py")
        or (len(parts) > 1 and parts[-2] == "observe")
    )


def metric_drift_findings(files, root: Path,
                          waiver_log: WaiverLog | None = None) -> list:
    """Cross-reference metric families: consumed-but-never-created is
    an ERROR (the consumer reads zeros forever); created-but-never-
    mentioned-anywhere-else is a WARNING (a dead family, or its
    consumer was renamed away)."""
    waiver_log = waiver_log or WaiverLog()
    scans = []
    sources = {}
    for file_path in files:
        file_path = Path(file_path)
        if _is_test_path(str(file_path)):
            continue
        try:
            source = file_path.read_text()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue
        scan = _MetricScan(str(file_path),
                           _is_consumer_path(file_path, root))
        scan.visit(tree)
        scans.append((scan, source, tree))
        sources[str(file_path)] = source
    # the mention corpus includes tests: a family consumed only by a
    # regression test is still consumed
    for test_file in sorted(root.glob("tests/*.py")):
        try:
            sources[str(test_file)] = test_file.read_text()
        except OSError:
            continue

    created = {}                     # name -> first (path, lineno)
    patterns = []                    # (compiled, path, lineno)
    consumed = []                    # (name, path, lineno)
    for scan, _source, _tree in scans:
        for name, lineno in scan.created:
            created.setdefault(name, (scan.path, lineno))
        for pattern, lineno in scan.patterns:
            patterns.append((re.compile(pattern), scan.path, lineno))
        for name, lineno in scan.consumed:
            consumed.append((name, scan.path, lineno))

    findings = []
    waivers = {}

    def _waived(path: str, lineno: int) -> bool:
        index = waivers.get(path)
        if index is None:
            index = waivers[path] = WaiverIndex(sources.get(path, ""))
        match = index.match("lint-metric-drift", lineno)
        if match is not None:
            waiver_log.mark_used(path, match)
            return True
        return False

    consumed_names = {name for name, _path, _line in consumed}
    for name, path, lineno in consumed:
        if name in created or name in METRIC_DRIFT_ALLOWLIST:
            continue
        if any(pattern.fullmatch(name) for pattern, _p, _l in patterns):
            continue
        if _waived(path, lineno):
            continue
        findings.append(Finding(
            "lint-metric-drift", ERROR, path, lineno,
            f"metric family {name!r} is consumed here but no registry "
            f"creation site defines it — renamed or never created "
            f"(add to METRIC_DRIFT_ALLOWLIST only for r06 hardware "
            f"fields)"))
    for name, (path, lineno) in sorted(created.items()):
        if name in consumed_names or name in METRIC_DRIFT_ALLOWLIST:
            continue
        mentions = sum(text.count(name)
                       for text in sources.values())
        if mentions > sources.get(path, "").count(name):
            # the name appears beyond its own defining file — some
            # consumer (test, script, doc string) still reads it
            continue
        if _waived(path, lineno):
            continue
        findings.append(Finding(
            "lint-metric-drift", WARNING, path, lineno,
            f"metric family {name!r} is created here but nothing in "
            f"the repo consumes or even mentions it — dead family, or "
            f"its consumer drifted"))
    return findings


# -- lint-wire-schema ---------------------------------------------------------

def wire_schema_snapshot() -> dict:
    """The envelope contract as one JSON-stable dict, built from the
    DECLARED constants in transport/wire.py (+ the trace marker's home
    in observe/tracing.py).  Committed as analysis/wire_schema.lock;
    lint-wire-schema fails on any difference."""
    from ..observe.tracing import TRACE_MARKER
    from ..transport import wire
    return {
        "version": 1,
        "magic": wire.MAGIC.decode("ascii"),
        "wire_version": wire.WIRE_VERSION,
        "buffer_marker": wire.BUFFER_MARKER,
        "buffer_marker_arity": wire.BUFFER_MARKER_ARITY,
        "trace_marker": TRACE_MARKER,
        "trace_fields_arity": wire.TRACE_FIELDS_ARITY,
        "tenant_marker": wire.TENANT_MARKER,
        "tenant_fields_arity": wire.TENANT_FIELDS_ARITY,
        "hop_entry_fields": list(wire.HOP_ENTRY_FIELDS),
        "hop_entry_optional": list(wire.HOP_ENTRY_OPTIONAL),
        "codecs": {
            name: {
                "dtypes": list(wire.WIRE_CODEC_DTYPES[name]),
                "rank": wire.WIRE_CODEC_RANK.get(name),
            } for name in sorted(wire.WIRE_CODECS)},
        "kv_transfer": {
            "command": wire.KV_TRANSFER_COMMAND,
            "batch_command": wire.KV_BATCH_COMMAND,
            "required_params": wire.KV_TRANSFER_PARAMS,
            "optional_params": ["chunk"],
            "schema": dict(wire.KV_TRANSFER_SCHEMA),
            "dtypes": {key: list(value) for key, value in
                       sorted(wire.KV_TRANSFER_DTYPES.items())},
            "rank": dict(sorted(wire.KV_TRANSFER_RANK.items())),
        },
        # session migration control legs (ISSUE 19): the offer + its
        # ack/done replies; the KV payload itself rides kv_transfer
        # above, so only the new commands and the offer arity lock
        "kv_migrate": {
            "command": wire.KV_MIGRATE_COMMAND,
            "ack_command": wire.KV_MIGRATE_ACK_COMMAND,
            "done_command": wire.KV_MIGRATE_DONE_COMMAND,
            "required_params": wire.KV_MIGRATE_PARAMS,
            "arrays": ["tokens", "history"],
        },
    }


def _runtime_consistency() -> list:
    """Cross-check the declared arities against what the encode paths
    actually build — the lock is only worth committing if the
    declaration cannot drift from the runtime either."""
    import numpy as np
    from ..transport import wire
    problems = []
    tenant = wire.tenant_fields("t", 2)
    if len(tenant) != wire.TENANT_FIELDS_ARITY:
        problems.append(
            f"tenant_fields() builds {len(tenant)} fields, declared "
            f"TENANT_FIELDS_ARITY={wire.TENANT_FIELDS_ARITY}")
    buffers: list = []
    marker = wire._extract(np.zeros((2,), np.int32), buffers)
    if len(marker) != wire.BUFFER_MARKER_ARITY:
        problems.append(
            f"_extract() builds a {len(marker)}-element buffer marker, "
            f"declared BUFFER_MARKER_ARITY={wire.BUFFER_MARKER_ARITY}")
    try:
        from ..observe.tracing import TraceContext
        fields = TraceContext(trace_id="t" * 32,
                              span_id="s" * 16).to_fields(0.0)
        if len(fields) != wire.TRACE_FIELDS_ARITY:
            problems.append(
                f"TraceContext.to_fields() builds {len(fields)} "
                f"fields, declared TRACE_FIELDS_ARITY="
                f"{wire.TRACE_FIELDS_ARITY}")
    except TypeError:
        problems.append("TraceContext signature changed — update the "
                        "wire-schema consistency probe")
    return problems


def _flatten(value, prefix: str = "") -> dict:
    if isinstance(value, dict):
        flat = {}
        for key in value:
            flat.update(_flatten(value[key],
                                 f"{prefix}.{key}" if prefix else key))
        return flat
    if isinstance(value, list):
        return {prefix: json.dumps(value)}
    return {prefix: value}


def wire_schema_findings(root: Path, lock_path: Path | None = None) \
        -> list:
    """Compare the runtime wire schema against the committed lock.
    Every divergent key is its own ERROR, so the failure names exactly
    which envelope field moved."""
    lock_path = lock_path or \
        Path(__file__).resolve().parent / WIRE_LOCK_NAME
    wire_path = str(root / "aiko_services_tpu" / "transport" / "wire.py")
    findings = []
    for problem in _runtime_consistency():
        findings.append(Finding("lint-wire-schema", ERROR, wire_path, 0,
                                problem))
    snapshot = wire_schema_snapshot()
    try:
        locked = json.loads(lock_path.read_text())
    except FileNotFoundError:
        findings.append(Finding(
            "lint-wire-schema", ERROR, str(lock_path), 0,
            "wire schema lock missing — run `python -m "
            "aiko_services_tpu.analysis --update-wire-lock` and commit "
            "the result"))
        return findings
    except (OSError, json.JSONDecodeError) as exc:
        findings.append(Finding(
            "lint-wire-schema", ERROR, str(lock_path), 0,
            f"wire schema lock unreadable: {exc}"))
        return findings
    flat_now, flat_locked = _flatten(snapshot), _flatten(locked)
    for key in sorted(set(flat_now) | set(flat_locked)):
        now, was = flat_now.get(key), flat_locked.get(key)
        if now == was:
            continue
        if key not in flat_locked:
            message = (f"wire schema field {key!r} = {now!r} is not in "
                       f"the lock — an envelope change must be a "
                       f"two-sided diff (--update-wire-lock)")
        elif key not in flat_now:
            message = (f"locked wire schema field {key!r} = {was!r} "
                       f"no longer exists in transport/wire.py")
        else:
            message = (f"wire schema drift at {key!r}: locked {was!r}, "
                       f"runtime {now!r} — changing the envelope "
                       f"requires regenerating the lock "
                       f"(--update-wire-lock)")
        findings.append(Finding("lint-wire-schema", ERROR, wire_path, 0,
                                message))
    return findings


def write_wire_lock(lock_path: Path | None = None) -> Path:
    lock_path = lock_path or \
        Path(__file__).resolve().parent / WIRE_LOCK_NAME
    lock_path.write_text(
        json.dumps(wire_schema_snapshot(), indent=2, sort_keys=True)
        + "\n")
    return lock_path
