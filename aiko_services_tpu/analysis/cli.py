# graft-check CLI: `python -m aiko_services_tpu.analysis ...`
#
#   --pipeline DEF.json    contract-check pipeline definitions (repeat)
#   --lint PATH            lint files/directories (repeat)
#   --self-check           the repo's own CI gate: lint the package +
#                          bench.py + scripts/ + tools/, run the
#                          interprocedural effect analysis, the
#                          metric-drift and wire-schema checkers, the
#                          bundled example pipelines, and the stale-
#                          waiver audit
#   --codec KEY=CODEC      wire codec hints for --pipeline checks
#   --format text|json     output format
#   --strict               treat warnings as errors
#   --baseline FILE        subtract acknowledged findings (see
#                          analysis/baseline.py); new findings still
#                          gate
#   --update-baseline      regenerate the baseline file from the
#                          current findings and exit 0
#   --update-wire-lock     regenerate analysis/wire_schema.lock from
#                          the declared wire constants and exit 0
#   --rules                print the lint rule catalog and exit
#
# Exit status: 0 = clean (warnings allowed unless --strict), 1 = findings
# at gating severity, 2 = usage error.

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .baseline import apply_baseline, load_baseline, write_baseline
from .drift import (metric_drift_findings, wire_schema_findings,
                    write_wire_lock)
from .effects import effect_findings
from .findings import ERROR, format_findings
from .graph_check import check_pipeline_file
from .lint import WaiverLog, lint_paths, rule_catalog

__all__ = ["main", "self_check_findings"]


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def _repo_root() -> Path:
    return _package_root().parent


def _looks_like_pipeline(pathname: Path) -> bool:
    try:
        data = json.loads(pathname.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return False
    return isinstance(data, dict) and "graph" in data and \
        "elements" in data


def _self_check_paths() -> list:
    """The repo's own lint surface: the package, bench.py, and the
    scripts/ and tools/ trees (soaks and A/B harnesses used to escape
    analysis entirely)."""
    root = _repo_root()
    paths = [_package_root()]
    for extra in ("bench.py", "scripts", "tools"):
        candidate = root / extra
        if candidate.exists():
            paths.append(candidate)
    return paths


def self_check_findings(waiver_log: WaiverLog | None = None) -> list:
    """The repo's own gate, all layers sharing one waiver log: the
    syntactic lint, the interprocedural effect analysis (call-graph
    propagation of blocking/transfer/alloc/wall-clock reach), the
    metric-drift and wire-schema drift checkers, the declared wire
    transfer schemas, the bundled example pipelines, and finally the
    stale-waiver audit over everything the passes recorded."""
    from .callgraph import iter_python_files
    from .graph_check import check_wire_schemas
    waiver_log = waiver_log if waiver_log is not None else WaiverLog()
    root = _repo_root()
    paths = _self_check_paths()
    findings = lint_paths(paths, waiver_log=waiver_log)
    findings.extend(effect_findings(paths, root=root,
                                    waiver_log=waiver_log))
    files = list(iter_python_files(paths))
    findings.extend(metric_drift_findings(files, root,
                                          waiver_log=waiver_log))
    findings.extend(wire_schema_findings(root))
    findings.extend(check_wire_schemas())
    examples = root / "examples"
    if examples.is_dir():
        for pathname in sorted(examples.rglob("*.json")):
            if _looks_like_pipeline(pathname):
                findings.extend(check_pipeline_file(str(pathname)))
    findings.extend(waiver_log.stale_findings())
    return findings


def _parse_codecs(entries) -> dict:
    hints = {}
    for entry in entries or []:
        key, _, codec = entry.partition("=")
        if not key or not codec:
            raise ValueError(f"--codec wants KEY=CODEC, got {entry!r}")
        hints[key] = codec
    return hints


def _resolve_baseline(argument: str) -> Path:
    """A relative --baseline resolves against the cwd first, then the
    package root — so the documented invocation
    `--baseline analysis/baseline.json` works from the repo root."""
    path = Path(argument)
    if path.is_absolute() or path.exists():
        return path
    fallback = _package_root() / argument
    return fallback if fallback.exists() else path


def _print_rule_catalog() -> None:
    for rule_id, severity, doc, example in rule_catalog():
        print(f"{rule_id:<24} {severity:<8} {doc}")  # graft: disable=lint-print
        if example:
            print(f"{'':<24} example: {example}")  # graft: disable=lint-print


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m aiko_services_tpu.analysis",
        description="graft-check: static pipeline contract checker and "
                    "event-loop lint")
    parser.add_argument("--pipeline", action="append", default=[],
                        metavar="DEF.json",
                        help="pipeline definition to contract-check")
    parser.add_argument("--lint", action="append", default=[],
                        metavar="PATH",
                        help="file or directory to lint (recursive)")
    parser.add_argument("--self-check", action="store_true",
                        help="run every analysis layer over the repo "
                             "(lint, effects, drift, examples)")
    parser.add_argument("--codec", action="append", default=[],
                        metavar="KEY=CODEC",
                        help="wire codec hint for --pipeline checks")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--strict", action="store_true",
                        help="warnings gate too")
    parser.add_argument("--baseline", metavar="FILE",
                        help="subtract acknowledged findings; new "
                             "findings still gate")
    parser.add_argument("--update-baseline", action="store_true",
                        help="regenerate --baseline FILE from the "
                             "current findings and exit 0")
    parser.add_argument("--update-wire-lock", action="store_true",
                        help="regenerate analysis/wire_schema.lock "
                             "and exit 0")
    parser.add_argument("--rules", action="store_true",
                        help="print the lint rule catalog and exit")
    args = parser.parse_args(argv)
    if args.rules:
        _print_rule_catalog()
        return 0
    if args.update_wire_lock:
        lock_path = write_wire_lock()
        # CLI user-facing output: graft: disable=lint-print
        print(f"graft-check: wrote {lock_path}")
        return 0
    if args.update_baseline and not args.baseline:
        print("--update-baseline needs --baseline FILE",
              file=sys.stderr)                # graft: disable=lint-print
        return 2
    if not (args.pipeline or args.lint or args.self_check):
        parser.print_usage(sys.stderr)
        # CLI user-facing output, not telemetry: graft: disable=lint-print
        print("nothing to do: give --pipeline, --lint, or --self-check",
              file=sys.stderr)
        return 2
    try:
        wire_codecs = _parse_codecs(args.codec)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)    # graft: disable=lint-print
        return 2

    findings = []
    for pathname in args.pipeline:
        findings.extend(check_pipeline_file(pathname,
                                            wire_codecs=wire_codecs))
    if args.lint:
        findings.extend(lint_paths(args.lint))
    if args.self_check:
        findings.extend(self_check_findings())

    if args.baseline:
        baseline_path = _resolve_baseline(args.baseline)
        if args.update_baseline:
            write_baseline(baseline_path, findings, _repo_root())
            # CLI user-facing output: graft: disable=lint-print
            print(f"graft-check: wrote {len(findings)} finding(s) to "
                  f"{baseline_path}")
            return 0
        try:
            entries = load_baseline(baseline_path)
        except (OSError, ValueError) as exc:
            print(f"graft-check: {exc}",
                  file=sys.stderr)            # graft: disable=lint-print
            return 2
        findings = apply_baseline(findings, entries, _repo_root(),
                                  baseline_path)

    if findings or args.format == "json":
        # json mode always emits a document ("[]" when clean) so
        # machine consumers can parse it — graft: disable=lint-print
        print(format_findings(findings, args.format))
    gating = [f for f in findings
              if f.severity == ERROR or args.strict]
    summary = f"graft-check: {len(findings)} finding(s), " \
              f"{len([f for f in findings if f.severity == ERROR])} " \
              f"error(s)"
    if args.format == "text":
        print(summary)                      # graft: disable=lint-print
    return 1 if gating else 0
