# graft-check CLI: `python -m aiko_services_tpu.analysis ...`
#
#   --pipeline DEF.json    contract-check pipeline definitions (repeat)
#   --lint PATH            lint files/directories (repeat)
#   --self-check           lint this package + contract-check the bundled
#                          example pipelines (the repo's own CI gate)
#   --codec KEY=CODEC      wire codec hints for --pipeline checks
#   --format text|json     output format
#   --strict               treat warnings as errors
#
# Exit status: 0 = clean (warnings allowed unless --strict), 1 = findings
# at gating severity, 2 = usage error.

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .findings import ERROR, format_findings
from .graph_check import check_pipeline_file
from .lint import lint_paths

__all__ = ["main", "self_check_findings"]


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def _looks_like_pipeline(pathname: Path) -> bool:
    try:
        data = json.loads(pathname.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return False
    return isinstance(data, dict) and "graph" in data and \
        "elements" in data


def self_check_findings() -> list:
    """The repo's own gate: lint the whole package, contract-check
    every bundled example pipeline definition, and prove the declared
    wire transfer schemas (KV transfer, ISSUE 14) agree with the
    runtime tables that enforce them."""
    from .graph_check import check_wire_schemas
    findings = lint_paths([_package_root()])
    findings.extend(check_wire_schemas())
    examples = _package_root().parent / "examples"
    if examples.is_dir():
        for pathname in sorted(examples.rglob("*.json")):
            if _looks_like_pipeline(pathname):
                findings.extend(check_pipeline_file(str(pathname)))
    return findings


def _parse_codecs(entries) -> dict:
    hints = {}
    for entry in entries or []:
        key, _, codec = entry.partition("=")
        if not key or not codec:
            raise ValueError(f"--codec wants KEY=CODEC, got {entry!r}")
        hints[key] = codec
    return hints


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m aiko_services_tpu.analysis",
        description="graft-check: static pipeline contract checker and "
                    "event-loop lint")
    parser.add_argument("--pipeline", action="append", default=[],
                        metavar="DEF.json",
                        help="pipeline definition to contract-check")
    parser.add_argument("--lint", action="append", default=[],
                        metavar="PATH",
                        help="file or directory to lint (recursive)")
    parser.add_argument("--self-check", action="store_true",
                        help="lint this package and check the bundled "
                             "example pipelines")
    parser.add_argument("--codec", action="append", default=[],
                        metavar="KEY=CODEC",
                        help="wire codec hint for --pipeline checks")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--strict", action="store_true",
                        help="warnings gate too")
    args = parser.parse_args(argv)
    if not (args.pipeline or args.lint or args.self_check):
        parser.print_usage(sys.stderr)
        # CLI user-facing output, not telemetry: graft: disable=lint-print
        print("nothing to do: give --pipeline, --lint, or --self-check",
              file=sys.stderr)
        return 2
    try:
        wire_codecs = _parse_codecs(args.codec)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)    # graft: disable=lint-print
        return 2

    findings = []
    for pathname in args.pipeline:
        findings.extend(check_pipeline_file(pathname,
                                            wire_codecs=wire_codecs))
    if args.lint:
        findings.extend(lint_paths(args.lint))
    if args.self_check:
        findings.extend(self_check_findings())

    if findings or args.format == "json":
        # json mode always emits a document ("[]" when clean) so
        # machine consumers can parse it — graft: disable=lint-print
        print(format_findings(findings, args.format))
    gating = [f for f in findings
              if f.severity == ERROR or args.strict]
    summary = f"graft-check: {len(findings)} finding(s), " \
              f"{len([f for f in findings if f.severity == ERROR])} " \
              f"error(s)"
    if args.format == "text":
        print(summary)                      # graft: disable=lint-print
    return 1 if gating else 0
